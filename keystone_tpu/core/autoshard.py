"""Automated sharding/placement search: cost-model-ranked plans replace the
hand-enumerated ladders.

The degradation ladders (core.memory.run_ladder) encode placement as two
hand-written lists: fused -> stepwise -> host-staged on one device, full
mesh -> collapsed mesh -> single device across chips.  That is KeystoneML's
pre-optimizer posture — operator choices written down instead of searched.
This module is the whole-pipeline-optimizer treatment for PLACEMENT
(Automap and the Learned Cost Model placement paper, PAPERS.md): given a
solve's candidate executions — every (data, model) factorization of the
live device set (parallel.mesh.enumerate_mesh_shapes) x sharding spec per
operand (from the program's avals, :func:`spec_candidates`) x execution
strategy (fused / stepwise / host-staged) — the search

1. **prunes** candidates with the zero-cost analytic batch preflight
   (core.memory.plan_bytes / plan_batch — no compile; a denied plan is
   free to reject, and the full compiled admission still guards whatever
   the ladder later selects);
2. **scores** survivors with the shared cost model
   (core.optimize.CostModel): an analytic roofline prior over per-chip
   bytes / FLOPs / dispatches / collective volume, multiplied by a learned
   per-(program, candidate) calibration fitted to MEASURED outcomes from
   the persistent plan-outcome log (``~/.keystone_plans.jsonl``, keyed by
   program fingerprint) — the model improves across runs;
3. **ranks** with a confidence margin: candidates whose predicted costs
   are within one margin FACTOR of the cheapest remaining candidate keep
   their prior (hand-ladder) order (:data:`UNTRAINED_MARGIN` cold,
   :data:`TRAINED_MARGIN` for pairs where BOTH sides carry >=
   :data:`MIN_TRAIN` direct measurements) — an untrained prior never
   deviates from the proven default on noise, so a searched fit is
   bit-identical to the hand ladder until real measurements argue
   otherwise; the resilience floor is pinned last regardless of score;
4. **runs** the ranked list through the SAME ``run_ladder`` contract the
   hand ladders use — per-tier compiled admission at selection, runtime
   RESOURCE_EXHAUSTED steps down the RANKED list one plan at a time
   (counted ``autoshard_stepdown``), typed errors propagate — and lands
   the full candidate table, deny/score rationale, and predicted-vs-actual
   cost of the chosen plan in the :class:`PlacementPlan` attached to the
   solver's ``FitReport``.

**Sharding specs are executable** (ISSUE 10): :func:`spec_candidates`
enumerates per-operand shardings from an aval's own dimensions, and
:func:`spec_pspec` / :func:`spec_sharding` lower a chosen spec string
(``"data@dim0"``, ``"model@dim1"``, ``"replicated"``) into the actual
``PartitionSpec`` / ``NamedSharding`` the mesh programs constrain their
operands with — so a :class:`Candidate` can carry a per-operand spec
assignment (``Candidate.specs``) that the solvers execute as a REAL
layout, not just a byte estimate.  The candidate space is then
(mesh factorization x strategy x spec assignment), still pruned by the
same zero-cost batch preflight (which already charges spec bytes) and
still run through the unchanged ``run_ladder`` contract.
``KEYSTONE_AUTOSHARD_SPECS=0`` restores the PR 9 posture (one hard-coded
layout per strategy; the spec dimension drops out of the enumeration).

**Calibration is cross-program** (ISSUE 10): below :data:`MIN_TRAIN`
direct measurements, a candidate's factor comes from a featurized ratio
regression (core.optimize.CalibrationModel) fitted over EVERY program's
logged outcomes — operand bytes, mesh axes, strategy, arithmetic
intensity from the roofline prior — so learning on one solve shape
transfers to unseen shapes.  The conservative-margin rules are
unchanged: only direct measurements tighten the margin, and an empty log
reproduces the hand ladder bit-for-bit.

``KEYSTONE_AUTOSHARD=0`` restores the hand ladders; ``fit(plan=...)``
overrides per call (``False`` hand, ``True`` force search, a
:class:`PlacementPlan` or name list replays a previous ranking).
``KEYSTONE_PLAN_LOG`` points the outcome log elsewhere (``off`` disables);
``KEYSTONE_PLAN_LOG_MAX`` caps its entry count (oldest-first compaction on
write).  The log is read ONCE per process: outcomes appended during a run
train the NEXT process, so a ranking can never silently change between a
baseline and a comparison fit inside one process (the chaos bit-equality
bar).
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import json
import logging
import os
import time
from typing import Any, Callable, Sequence

import numpy as np

from . import memory as kmem
from . import optimize as kopt
from . import profiler as kprof
from . import trace
from .resilience import counters

_logger = logging.getLogger("keystone_tpu.autoshard")

#: env var: "0"/"off"/"false" restores the hand ladders process-wide.
AUTOSHARD_ENV = "KEYSTONE_AUTOSHARD"

#: env var: "0"/"off"/"false" drops the per-operand SPEC dimension from
#: the candidate enumeration (the PR 9 posture: one layout per strategy).
SPECS_ENV = "KEYSTONE_AUTOSHARD_SPECS"

#: env var: plan-outcome log path; default ``~/.keystone_plans.jsonl``;
#: "0"/"off"/"none" disables persistence.
PLAN_LOG_ENV = "KEYSTONE_PLAN_LOG"
_DEFAULT_PLAN_LOG = "~/.keystone_plans.jsonl"

#: env var: plan-outcome log entry cap (oldest-first compaction on write);
#: "0"/"off" disables capping.
PLAN_LOG_MAX_ENV = "KEYSTONE_PLAN_LOG_MAX"
_DEFAULT_PLAN_LOG_MAX = 20_000

#: measurements per (fingerprint, candidate) before its calibration counts.
MIN_TRAIN = 3
#: cold-start ranking margin: an untrained analytic score must beat the
#: cheapest remaining candidate by this FACTOR before reordering past a
#: prior-earlier plan — the guarantee that a searched fit without
#: measurements reproduces the hand ladder's choice bit-for-bit.
UNTRAINED_MARGIN = 4.0
#: margin for a pair of candidates that BOTH carry >= MIN_TRAIN direct
#: measured outcomes — only like-for-like measured comparisons get the
#: tight margin; any pair with an unmeasured side keeps the cold one.
TRAINED_MARGIN = 1.15

#: bound on how much of the log one process will read back (newest wins).
_MAX_LOG_RECORDS = 50_000


def enabled() -> bool:
    """Search is the default; ``KEYSTONE_AUTOSHARD=0`` restores the hand
    ladders."""
    return os.environ.get(AUTOSHARD_ENV, "").strip().lower() not in (
        "0", "off", "false",
    )


def specs_enabled() -> bool:
    """Spec-assignment candidates are enumerated by default when the
    search runs; ``KEYSTONE_AUTOSHARD_SPECS=0`` restores the PR 9
    one-layout-per-strategy candidate space."""
    return os.environ.get(SPECS_ENV, "").strip().lower() not in (
        "0", "off", "false",
    )


# -- program fingerprints ------------------------------------------------------


def fingerprint(label: str, *parts) -> str:
    """Stable 16-hex-char fingerprint of a solve program's cost identity:
    the label plus whatever shapes/dtypes/statics/device description the
    caller folds in.  Same fingerprint => the plan log's measurements are
    comparable => same ranking under a fixed device set (the determinism
    contract the tests pin)."""
    blob = json.dumps([label, *map(str, parts)], sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def device_fingerprint(devices=None) -> str:
    """``'cpu x8'``-style description of the device set a plan assumed."""
    try:
        if devices is None:
            import jax

            devices = jax.devices()
        devices = list(devices)
        kind = getattr(devices[0], "device_kind", "unknown")
        return f"{kind} x{len(devices)}"
    except Exception:  # noqa: BLE001 — no backend yet
        return "unknown x0"


# -- sharding-spec enumeration from avals --------------------------------------


def spec_candidates(aval, mesh_shape: dict) -> list[dict]:
    """Candidate shardings for ONE operand aval under a (data, model) mesh
    shape — generated from the aval's dimensions, not a hand list: the data
    axis over any evenly-divisible dim, the model axis over any other
    evenly-divisible dim, and replicated (always legal).  Each entry
    carries the spec's per-chip bytes, the quantity the cost model charges.
    """
    shape = tuple(int(d) for d in aval.shape)
    itemsize = np.dtype(aval.dtype).itemsize
    total = int(np.prod(shape)) * itemsize if shape else itemsize
    out = [{"spec": "replicated", "per_chip_bytes": total}]
    d_sz = int(mesh_shape.get("data", 1))
    m_sz = int(mesh_shape.get("model", 1))
    for dim, n in enumerate(shape):
        if d_sz > 1 and n % d_sz == 0:
            out.append({
                "spec": f"data@dim{dim}",
                "per_chip_bytes": total // d_sz,
            })
        if m_sz > 1 and n % m_sz == 0:
            out.append({
                "spec": f"model@dim{dim}",
                "per_chip_bytes": total // m_sz,
            })
    return out


def best_spec(aval, mesh_shape: dict) -> dict:
    """The minimum-per-chip-bytes legal sharding for one aval — what the
    analytic byte accounting assumes a candidate mesh can achieve for a
    shardable operand (replicated when nothing divides)."""
    cands = spec_candidates(aval, mesh_shape)
    return min(cands, key=lambda c: (c["per_chip_bytes"], c["spec"]))


# -- spec strings -> executable layouts ----------------------------------------
#
# A spec string names ONE mesh axis over ONE operand dimension
# ("data@dim0", "model@dim1") or full replication ("replicated") — the
# exact vocabulary :func:`spec_candidates` enumerates from avals.  The
# lowerers below turn a CHOSEN spec into the jax objects the mesh
# programs execute with, so the byte accounting and the executed layout
# can never drift: both read the same string.


def spec_pspec(spec: str, ndim: int):
    """Lower one spec string to the ``PartitionSpec`` it names."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS, MODEL_AXIS

    if spec == "replicated":
        return P(*([None] * ndim))
    axis, sep, dim = spec.partition("@dim")
    if not sep or axis not in ("data", "model") or not dim.isdigit():
        raise ValueError(
            f"bad sharding spec {spec!r} (want 'replicated', 'data@dimN' "
            "or 'model@dimN')"
        )
    i = int(dim)
    if i >= ndim:
        raise ValueError(f"spec {spec!r} names dim {i} of a {ndim}-d operand")
    parts: list = [None] * ndim
    parts[i] = DATA_AXIS if axis == "data" else MODEL_AXIS
    return P(*parts)


def spec_sharding(spec: str, mesh, ndim: int):
    """Lower one spec string to a ``NamedSharding`` on ``mesh`` — the
    layout the solvers constrain an operand with when a spec-assignment
    candidate executes."""
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, spec_pspec(spec, ndim))


def spec_chip_bytes(shape, dtype, spec: str, mesh_shape: dict) -> int:
    """Analytic per-chip bytes of one operand under one spec — the figure
    a spec-assignment candidate's hints charge (and the quantity the
    lower-bound regression test pins against the compiled
    ``memory_analysis``).  The named dimension must divide evenly; callers
    enumerate via :func:`spec_candidates`, which only emits legal specs."""
    shape = tuple(int(d) for d in shape)
    total = int(np.prod(shape)) * np.dtype(dtype).itemsize if shape else (
        np.dtype(dtype).itemsize
    )
    if spec == "replicated":
        return total
    axis, _, dim = spec.partition("@dim")
    size = int(mesh_shape.get(axis, 1))
    n = shape[int(dim)]
    if size <= 1:
        return total
    if n % size:
        raise ValueError(
            f"spec {spec!r} does not divide dim of size {n} by {size}"
        )
    return total // size


def spec_tag(specs: dict | None) -> str:
    """Compact human tag for a spec assignment (candidate names, the
    plan_view spec column): ``'labels=model@dim1,models=rep'``."""
    if not specs:
        return "default"
    return ",".join(
        f"{k}={'rep' if v == 'replicated' else v}"
        for k, v in sorted(specs.items())
    )


# -- the plan-outcome log ------------------------------------------------------


def plan_log_path() -> str | None:
    raw = os.environ.get(PLAN_LOG_ENV, "").strip()
    if raw.lower() in ("0", "off", "none"):
        return None
    return os.path.expanduser(raw or _DEFAULT_PLAN_LOG)


def hermetic_plan_log() -> str:
    """Point the plan-outcome log at a fresh throwaway file and forget any
    cached read.  For measurement/chaos drivers (bench sections,
    tools/chaos_run.py): their fixed-seed synthetic fits must neither
    TRAIN the operator's real log (three bench rounds would calibrate the
    bench fingerprints and start reordering the very ranking the driver
    asserts is hand-identical) nor evict real workload records from its
    bounded tail."""
    import tempfile

    path = os.path.join(
        tempfile.mkdtemp(prefix="keystone_plans_hermetic_"), "plans.jsonl"
    )
    os.environ[PLAN_LOG_ENV] = path
    clear_outcome_cache()
    return path


def plan_log_max() -> int | None:
    """Entry cap on the plan-outcome log (``KEYSTONE_PLAN_LOG_MAX``;
    default 20k, ``0``/``off`` disables).  Raises ``ValueError`` for a
    malformed or negative value (same fail-fast grammar as the other
    ``KEYSTONE_*`` numeric knobs); the append path catches it — telemetry
    never crashes a solve."""
    raw = os.environ.get(PLAN_LOG_MAX_ENV, "").strip()
    if not raw:
        return _DEFAULT_PLAN_LOG_MAX
    if raw.lower() in ("0", "off", "none"):
        return None
    try:
        val = int(raw)
    except ValueError:
        raise ValueError(
            f"{PLAN_LOG_MAX_ENV}={raw!r} is not an integer"
        ) from None
    if val < 1:
        raise ValueError(f"{PLAN_LOG_MAX_ENV}={raw!r} must be >= 1 (or 'off')")
    return val


#: newest records kept per (fingerprint, candidate) when compaction must
#: drop history: enough for a stable median over MIN_TRAIN-sized tails
#: (an odd count keeps the median an actual sample).
_COMPACT_KEEP_PAIR = 9


@contextlib.contextmanager
def _log_lock(path: str):
    """Advisory exclusive lock (sidecar ``<path>.lock``) serializing log
    appends against compaction's read-rewrite-replace: without it, a
    record another process appends between compaction's read and its
    ``os.replace`` would vanish silently.  Best-effort — platforms
    without ``fcntl`` (or an unwritable sidecar) fall back to unlocked
    appends, the pre-cap behavior."""
    lf = None
    try:
        try:
            import fcntl

            lf = open(path + ".lock", "a")
            fcntl.flock(lf, fcntl.LOCK_EX)
        except (ImportError, OSError):
            lf = None
        yield
    finally:
        if lf is not None:
            try:
                lf.close()  # closing the fd releases the flock
            except OSError:
                pass


def compact_log(path: str, cap: int) -> int:
    """Oldest-first compaction of the outcome log to a watermark BELOW
    ``cap`` (~90%, so the headroom amortizes the next O(entries) recount
    across many appends instead of re-reading per append once the log
    saturates).  Three passes: (1) per (fingerprint, candidate) pair,
    drop all but the newest :data:`_COMPACT_KEEP_PAIR` records — the
    median the calibration reads is computed over a pair's newest ratios,
    so trimming a pair's deep history leaves its factor stable; (2) if
    still over the watermark, evict whole pairs, least-recently-written
    first — but never the last one; (3) a lone surviving pair still over
    the watermark trims to its newest records.  The log is never wiped
    outright, whatever the cap.  Atomic rewrite (tmp + rename); returns
    the surviving record count."""
    with _log_lock(path):
        return _compact_locked(path, cap)


def _compact_locked(path: str, cap: int) -> int:
    try:
        with open(path) as f:
            lines = [ln for ln in (l.strip() for l in f) if ln]
    except OSError:
        return 0
    if len(lines) <= cap:
        return len(lines)
    target = max(1, cap - max(1, cap // 10))
    parsed: list = []
    for i, ln in enumerate(lines):
        try:
            r = json.loads(ln)
        except json.JSONDecodeError:
            continue  # a torn line never survives compaction
        parsed.append((i, (r.get("fingerprint"), r.get("candidate")), ln))
    by_pair: dict = {}
    for i, pair, ln in parsed:
        by_pair.setdefault(pair, []).append((i, ln))
    # pass 1: newest records per pair (file order = age order); a tiny
    # cap bounds the per-pair tail too, so one pair cannot overflow it
    keep = max(1, min(_COMPACT_KEEP_PAIR, target))
    kept_pairs = {p: rows[-keep:] for p, rows in by_pair.items()}
    # pass 2: whole-pair eviction, least-recently-written pair first
    pairs_by_recency = sorted(kept_pairs, key=lambda p: kept_pairs[p][-1][0])
    total = sum(len(rows) for rows in kept_pairs.values())
    for p in pairs_by_recency:
        if total <= target or len(kept_pairs) == 1:
            break
        total -= len(kept_pairs.pop(p))
    if total > target:  # pass 3: one pair left — trim, never wipe
        p = next(iter(kept_pairs))
        kept_pairs[p] = kept_pairs[p][-target:]
    survivors = sorted(
        (row for rows in kept_pairs.values() for row in rows),
        key=lambda row: row[0],
    )
    tmp = f"{path}.compact.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write("".join(ln + "\n" for _i, ln in survivors))
    os.replace(tmp, path)
    return len(survivors)


#: floor on one serialized outcome record's size — the unit converting
#: "entries of headroom" into "bytes of growth" for the cap prechecks.
_MIN_RECORD_BYTES = 64

#: path -> byte size below which the file PROVABLY holds <= cap entries
#: (set after each count: current size + headroom * _MIN_RECORD_BYTES).
#: Bounds the O(entries) recount to once per cap's-worth of growth
#: instead of once per append — the append path is a solve's finish path.
_compact_skip: dict[str, int] = {}


def append_outcome(record: dict) -> None:
    """Best-effort append of one plan outcome to the persistent log,
    compacting first when the log exceeds ``KEYSTONE_PLAN_LOG_MAX``
    entries (oldest records give way; per-pair median tails survive).  A
    broken log path — or a malformed cap env — degrades counted
    (``plan_log_write_failed``): the solve's result never depends on
    telemetry landing."""
    path = plan_log_path()
    if path is None:
        return
    try:
        parent = os.path.dirname(os.path.abspath(path))
        if parent:
            os.makedirs(parent, exist_ok=True)
        cap = plan_log_max()
        with _log_lock(path):
            # The whole cap-check + compact + append sequence holds the
            # log lock, so compaction's read-rewrite-replace can never
            # swallow a record another process appends concurrently.
            if cap is not None and os.path.exists(path):
                size = os.path.getsize(path)
                floor = max(
                    cap * _MIN_RECORD_BYTES, _compact_skip.get(path, 0)
                )
                if size > floor:
                    kept = _compact_locked(path, cap)
                    # Convert the entry headroom the watermark bought
                    # into bytes of growth using the OBSERVED mean record
                    # size (floored at _MIN_RECORD_BYTES) — real records
                    # carry the feature vector and run ~400-600 bytes, so
                    # the 64-byte floor alone would re-trigger the
                    # O(entries) recount within a couple of appends on a
                    # saturated log.
                    size_now = os.path.getsize(path)
                    rec_bytes = max(
                        _MIN_RECORD_BYTES, size_now // max(1, kept)
                    )
                    _compact_skip[path] = size_now + (
                        max(0, cap - kept) * rec_bytes
                    )
            with open(path, "a") as f:
                f.write(json.dumps(record) + "\n")
    except (OSError, ValueError) as e:
        counters.record("plan_log_write_failed", f"{path}: {e}")


#: path -> parsed records, filled once per process (see module docstring:
#: in-process stability is what keeps baseline-vs-faulted comparisons
#: bit-equal; fresh measurements train the NEXT process).
_outcome_cache: dict[str, list] = {}


def load_outcomes(path: str | None = None) -> list[dict]:
    path = path if path is not None else plan_log_path()
    if path is None:
        return []
    cached = _outcome_cache.get(path)
    if cached is not None:
        return cached
    records: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # a torn tail line is not an error
    except OSError:
        records = []
    records = records[-_MAX_LOG_RECORDS:]
    _outcome_cache[path] = records
    return records


def clear_outcome_cache() -> None:
    """Test seam: forget the once-per-process log read."""
    _outcome_cache.clear()
    _ratio_cache.clear()
    _model_cache.clear()
    _drift_model_cache.clear()
    _compact_skip.clear()


#: path -> ({(fingerprint, candidate): ratios}, {fingerprint: ratios},
#: model_rows) — one pass over the log per process instead of a rescan per
#: candidate (the search's O(candidates) calibration lookups must stay
#: O(1) against a log grown toward _MAX_LOG_RECORDS, or the scan itself
#: would eat the <5% search-overhead budget).
_ratio_cache: dict[str, tuple[dict, dict, list]] = {}

#: path -> fitted cross-program model (or None when the log cannot
#: support one) — the regression is fit once per process, like the read.
_model_cache: dict[str, object] = {}


def _ratio_index(path: str | None) -> tuple[dict, dict, list]:
    key = path if path is not None else (plan_log_path() or "")
    cached = _ratio_cache.get(key)
    if cached is not None:
        return cached
    by_pair: dict = {}
    by_fp: dict = {}
    rows: list = []
    for r in load_outcomes(path):
        if not (
            r.get("outcome") == "ok"
            and r.get("predicted_seconds")
            and r.get("measured_seconds")
        ):
            continue
        # The regression learns measured vs the RAW analytic prior (the
        # quantity features describe); pre-calibration records fall back
        # to predicted (factor 1.0 at the time, so the two coincide).
        ratio = r["measured_seconds"] / r["predicted_seconds"]
        fp = r.get("fingerprint")
        by_pair.setdefault((fp, r.get("candidate")), []).append(ratio)
        by_fp.setdefault(fp, []).append(ratio)
        feats = r.get("features")
        raw = r.get("raw_seconds")
        if isinstance(feats, dict) and feats:
            rows.append((
                fp,
                feats,
                r["measured_seconds"] / raw if raw else ratio,
            ))
    _ratio_cache[key] = (by_pair, by_fp, rows)
    return by_pair, by_fp, rows


def model_rows(path: str | None = None) -> list:
    """The cross-program training rows the log holds:
    ``[(fingerprint, features, measured/raw_ratio)]`` over successful
    outcomes that carried a feature vector (bench drives the
    trained-on-A-predicted-on-B error from these)."""
    return list(_ratio_index(path)[2])


# -- the HBM watermark drift calibration (ISSUE 14) ----------------------------

#: path -> fitted byte-drift model (or None) — like _model_cache, read and
#: fit once per process; fresh drift rows train the NEXT process.
_drift_model_cache: dict[str, object] = {}


def hbm_features(
    argument_bytes: float,
    temp_bytes: float,
    output_bytes: float,
    mesh_axes: dict | None,
) -> dict:
    """Featurize one program's CHARGED byte composition for the byte-drift
    calibration — the same vector shape whether the row comes from a
    watermark audit (``core.profiler.audit_plan``, the MemoryPlan side) or
    a search candidate's hints (the scoring side), so train and predict
    can never drift apart on feature semantics."""
    charged = float(argument_bytes) + float(temp_bytes) + float(output_bytes)
    return {
        "kind": "hbm",
        "log_charged": float(np.log1p(charged)),
        "log_args": float(np.log1p(float(argument_bytes))),
        "log_temp": float(np.log1p(float(temp_bytes))),
        "log_out": float(np.log1p(float(output_bytes))),
        "data_axis": float((mesh_axes or {}).get("data", 1)),
        "model_axis": float((mesh_axes or {}).get("model", 1)),
    }


def drift_rows(path: str | None = None) -> list:
    """The plan-vs-actual HBM drift evidence the log holds:
    ``[(fingerprint, features, watermark/charged_ratio)]`` over the
    ``outcome:"hbm_drift"`` rows ``core.profiler.audit_plan`` appends —
    the byte-side analog of :func:`model_rows`."""
    rows = []
    for r in load_outcomes(path):
        if r.get("outcome") != "hbm_drift":
            continue
        ratio = r.get("drift_ratio")
        feats = r.get("features")
        if ratio and ratio > 0 and isinstance(feats, dict) and feats:
            rows.append((r.get("fingerprint"), feats, float(ratio)))
    return rows


def _drift_model(path: str | None = None):
    """The fitted byte-drift calibration (optimize.CalibrationModel over
    :func:`drift_rows`), or None when the log holds too little evidence —
    same thresholds as the time model, and the same empty-log guarantee:
    no drift rows means factor 1.0 everywhere, so an untrained search
    still reproduces the hand ladder bit-for-bit."""
    key = path if path is not None else (plan_log_path() or "")
    if key in _drift_model_cache:
        return _drift_model_cache[key]
    rows = drift_rows(path)
    model = None
    if (
        len(rows) >= kopt.MIN_MODEL_ROWS
        and len({fp for fp, _f, _r in rows}) >= 2
    ):
        model = kopt.CalibrationModel.fit_rows(rows)
    _drift_model_cache[key] = model
    return model


def drift_factor(features: dict, path: str | None = None) -> float:
    """Predicted watermark/charged ratio for one byte-composition feature
    vector (1.0 with no trained model)."""
    model = _drift_model(path)
    if model is None:
        return 1.0
    return model.predict_factor(features)


def _cross_program_model(path: str | None):
    """The fitted cross-program calibration (core.optimize
    CalibrationModel), or ``None`` when the log holds too few featurized
    outcomes or only one program — transfer needs >= 2 fingerprints by
    definition, and a single-program fit would just shadow the pooled
    median with extra variance."""
    key = path if path is not None else (plan_log_path() or "")
    if key in _model_cache:
        return _model_cache[key]
    rows = _ratio_index(path)[2]
    model = None
    if (
        len(rows) >= kopt.MIN_MODEL_ROWS
        and len({fp for fp, _f, _r in rows}) >= 2
    ):
        model = kopt.CalibrationModel.fit_rows(rows)
    _model_cache[key] = model
    return model


def plan_features(kind: str, mesh_axes: dict | None, hints: dict) -> dict:
    """Featurize one candidate for the cross-program calibration model:
    log-domain operand bytes / FLOPs / dispatches / transfer volumes, the
    mesh factorization, the arithmetic intensity the roofline prior sees,
    and the strategy kind — the quantities that transfer between solve
    shapes, unlike a (fingerprint, candidate) key."""
    b = lambda k: float(hints.get(k, 0) or 0)  # noqa: E731
    touched = b("arg_bytes") + b("temp_bytes") + b("out_bytes")
    flops = b("flops")
    feats = {
        "kind": kind,
        "log_bytes": float(np.log1p(touched)),
        "log_flops": float(np.log1p(flops)),
        "log_dispatches": float(np.log1p(b("dispatches") or 1.0)),
        "log_h2d": float(np.log1p(b("h2d_bytes"))),
        "log_coll": float(np.log1p(b("coll_bytes"))),
        "log_ai": float(np.log((flops + 1.0) / (touched + 1.0))),
        "data_axis": float((mesh_axes or {}).get("data", 1)),
        "model_axis": float((mesh_axes or {}).get("model", 1)),
    }
    return feats


def calibrate(
    fp: str,
    candidate: str,
    features: dict | None = None,
    path: str | None = None,
) -> tuple[float, int, str]:
    """``(factor, direct_samples, source)`` for one candidate.

    Priority ladder — most specific evidence first, each rung a strict
    superset of what the rung below knows:

    1. **direct** — >= :data:`MIN_TRAIN` measured outcomes of THIS
       (fingerprint, candidate) pair: their median ratio (the PR 9 rule,
       and the only rung that tightens the ranking margin);
    2. **model** — the cross-program regression
       (:func:`_cross_program_model`) evaluated on the candidate's
       features: learning from OTHER programs/shapes transfers here;
    3. **pooled** — the program-level median (every candidate of the
       fingerprint pooled): a CONSTANT factor across uncalibrated
       siblings, shifting absolute predictions toward honesty without
       reordering them;
    4. **none** — factor 1.0 (the raw analytic prior stands).

    Training is one-sided — only plans that actually RAN log outcomes —
    which is why rungs 2-3 exist: without them the measured winner would
    absorb its real slowdown while unmeasured competitors kept optimistic
    raw priors, and the ranking would drift toward whatever never ran.
    The returned sample count is the DIRECT count — it drives the
    per-pair trained margin, which no fallback rung may tighten."""
    by_pair, by_fp, _rows = _ratio_index(path)
    direct = by_pair.get((fp, candidate), ())
    if len(direct) >= MIN_TRAIN:
        return float(np.median(direct)), len(direct), "direct"
    if features is not None:
        model = _cross_program_model(path)
        if model is not None:
            return model.predict_factor(features), len(direct), "model"
    pooled = by_fp.get(fp, ())
    if len(pooled) >= MIN_TRAIN:
        return float(np.median(pooled)), len(direct), "pooled"
    return 1.0, len(direct), "none"


def calibration(fp: str, candidate: str, path: str | None = None) -> tuple[float, int]:
    """Back-compat view of :func:`calibrate` without features (direct ->
    pooled -> 1.0): ``(factor, direct_samples)``."""
    factor, n, _source = calibrate(fp, candidate, path=path)
    return factor, n


# -- candidates and the plan record --------------------------------------------


@dataclasses.dataclass
class Candidate:
    """One executable placement: a mesh shape (or none) x execution
    strategy, with the lazy compiled preflight / run closures the ladder
    consumes and the analytic cost hints the search scores."""

    name: str
    kind: str  #: "fused_mesh" | "fused" | "stepwise" | "host_staged" | ...
    plan: Callable[[], "kmem.MemoryPlan"]
    run: Callable[["kmem.MemoryPlan"], Any]
    #: analytic per-chip cost hints (CostModel.predict_seconds keys) plus
    #: the prune figures plan_bytes charges (arg/temp/out/extra/resident).
    hints: dict = dataclasses.field(default_factory=dict)
    mesh_axes: dict | None = None
    prior_rank: int = 0  #: hand-ladder position (ties resolve to this)
    floor: bool = False  #: the resilience backstop — always ranked last
    hand: bool = True  #: hand-ladder member (its prunes land in FitReport)
    #: per-operand sharding-spec assignment this candidate EXECUTES
    #: (operand name -> spec string, e.g. {"labels": "model@dim1"});
    #: ``None`` = the strategy's default layout.  The solver's run closure
    #: lowers these through :func:`spec_sharding` — the same strings the
    #: hints' byte accounting charged.
    specs: dict | None = None


@dataclasses.dataclass
class CandidateRecord:
    """One row of the plan's candidate table — the deny/score rationale."""

    name: str
    kind: str
    mesh: dict | None
    prior_rank: int
    pruned: bool
    reason: str  #: deny reason when pruned, score rationale otherwise
    predicted_seconds: float | None = None
    raw_seconds: float | None = None  #: analytic prior before calibration
    calibration: float = 1.0
    samples: int = 0  #: DIRECT measured outcomes behind the calibration
    #: which rung produced the factor: "direct" | "model" | "pooled" | "none"
    calibration_source: str = "none"
    #: watermark-drift calibration applied to the scored temp bytes
    #: (1.0 = no trained byte-drift model; see autoshard.drift_factor)
    byte_drift: float = 1.0
    rank: int | None = None  #: position in the execution ranking
    measured_seconds: float | None = None  #: filled when this plan RAN
    outcome: str | None = None  #: "ok" | "oom" | "denied" after the run
    #: the spec assignment this candidate executes (None = default layout)
    specs: dict | None = None
    #: cross-program feature vector (what the calibration model consumed
    #: and the outcome log persists for the NEXT process's training)
    features: dict | None = None

    def record(self) -> dict:
        out = dataclasses.asdict(self)
        for k in ("predicted_seconds", "raw_seconds", "measured_seconds"):
            if out[k] is not None:
                out[k] = round(out[k], 6)
        out["calibration"] = round(self.calibration, 4)
        if out["features"] is not None:
            out["features"] = {
                k: (round(v, 4) if isinstance(v, float) else v)
                for k, v in out["features"].items()
            }
        return out


@dataclasses.dataclass
class PlacementPlan:
    """The search's audit trail (FitReport's placement leg): every
    enumerated candidate with its deny/score rationale, the ranking that
    actually executed, and the chosen plan's predicted-vs-actual cost."""

    label: str
    fingerprint: str
    devices: str
    trained: bool
    margin: float
    candidates: list  #: list[CandidateRecord], prior order
    ranking: list  #: candidate names, execution order (floor last)
    search_seconds: float = 0.0
    chosen: str | None = None
    predicted_seconds: float | None = None
    measured_seconds: float | None = None
    prediction_error: float | None = None  #: predicted / measured
    #: name -> the zero-cost analytic MemoryPlan the batch preflight
    #: produced (pruned candidates hand it straight to the ladder walk —
    #: a pruned plan is denied for free, never re-planned or compiled).
    analytic_plans: dict = dataclasses.field(
        default_factory=dict, repr=False, compare=False
    )

    def candidate(self, name: str) -> CandidateRecord | None:
        for c in self.candidates:
            if c.name == name:
                return c
        return None

    def record(self) -> dict:
        return {
            "label": self.label,
            "fingerprint": self.fingerprint,
            "devices": self.devices,
            "trained": self.trained,
            "margin": self.margin,
            "search_seconds": round(self.search_seconds, 6),
            "ranking": list(self.ranking),
            "chosen": self.chosen,
            "predicted_seconds": (
                round(self.predicted_seconds, 6)
                if self.predicted_seconds is not None else None
            ),
            "measured_seconds": (
                round(self.measured_seconds, 6)
                if self.measured_seconds is not None else None
            ),
            "prediction_error": (
                round(self.prediction_error, 4)
                if self.prediction_error is not None else None
            ),
            "candidates": [c.record() for c in self.candidates],
        }

    def to_json(self) -> str:
        return json.dumps(self.record())

    def summary(self) -> str:
        s = (
            f"autoshard {self.label}[{self.fingerprint}]: "
            f"{len(self.ranking)}/{len(self.candidates)} candidates ranked"
            f" ({'trained' if self.trained else 'untrained'} margin "
            f"{self.margin}x), head={self.ranking[0] if self.ranking else None}"
        )
        if self.chosen is not None:
            s += f", chosen={self.chosen}"
        if self.prediction_error is not None:
            s += f", prediction_error={self.prediction_error:.2f}x"
        return s


# -- search + ranked execution -------------------------------------------------


def _margin_order(body: list) -> list:
    """Margin-aware selection order over ``(Candidate, CandidateRecord)``
    pairs: at each step, among the remaining candidates whose predicted
    cost is within the confidence margin of the CHEAPEST remaining one,
    the lowest prior (hand) rank wins.  Relative margins (not absolute
    buckets — two scores a hair apart must never split across a bucket
    edge and reorder) and per-pair trained-ness: the tight
    :data:`TRAINED_MARGIN` applies only when BOTH the candidate and the
    cheapest one carry >= :data:`MIN_TRAIN` direct measurements."""
    ordered: list = []
    remaining = sorted(body, key=lambda sr: sr[1].prior_rank)
    while remaining:
        best = min(remaining, key=lambda sr: (sr[1].predicted_seconds,
                                              sr[1].prior_rank))
        def margin(sr, best=best):
            both_trained = (
                sr[1].samples >= MIN_TRAIN and best[1].samples >= MIN_TRAIN
            )
            return TRAINED_MARGIN if both_trained else UNTRAINED_MARGIN

        pick = min(
            (
                sr for sr in remaining
                if sr[1].predicted_seconds
                <= best[1].predicted_seconds * margin(sr)
            ),
            key=lambda sr: sr[1].prior_rank,
        )
        ordered.append(pick)
        remaining.remove(pick)
    return ordered


def search(
    label: str,
    candidates: Sequence[Candidate],
    *,
    fingerprint: str,
    budget: int | None | object = kmem._UNSET,
    model: "kopt.CostModel | None" = None,
) -> PlacementPlan:
    """Enumerate -> prune -> score -> rank.  Pure decision pass: nothing is
    compiled and nothing runs — see :func:`run_search` for execution."""
    t0 = time.perf_counter()
    model = model if model is not None else kopt.CostModel.for_devices()
    records: list[CandidateRecord] = []
    survivors: list[tuple[Candidate, CandidateRecord]] = []
    with trace.span("autoshard.search", cat="plan", label=label):
        # 1. zero-cost batch preflight: analytic per-chip bytes vs budget.
        analytic = kmem.plan_batch([
            (
                c.name,
                lambda c=c: kmem.plan_bytes(
                    f"autoshard:{c.name}",
                    # LOWER bound of the compiled admission (see
                    # plan_bytes): donated/aliased argument bytes are
                    # credited out so the prune can never deny a plan the
                    # full preflight would admit.
                    argument_bytes=max(
                        0,
                        c.hints.get("arg_bytes", 0)
                        - c.hints.get("alias_bytes", 0),
                    ),
                    temp_bytes=c.hints.get("temp_bytes", 0),
                    extra_bytes=c.hints.get("extra_bytes", 0),
                    resident_bytes=c.hints.get("resident_bytes", 0),
                    budget=budget,
                ),
            )
            for c in candidates
        ])
        trained = True
        for c in candidates:
            mp = analytic[c.name]
            rec = CandidateRecord(
                name=c.name,
                kind=c.kind,
                mesh=dict(c.mesh_axes) if c.mesh_axes else None,
                prior_rank=c.prior_rank,
                pruned=not mp.admitted and not c.floor,
                reason=mp.reason,
                specs=dict(c.specs) if c.specs else None,
            )
            records.append(rec)
            if rec.pruned:
                rec.outcome = "denied"
                continue
            # 2. score: analytic roofline prior x learned calibration
            # (direct median, else the cross-program feature regression,
            # else the program-pooled median — see calibrate()).  The
            # scored TEMP bytes first pass through the byte-drift
            # calibration learned from HBM watermark audits
            # (core.profiler.audit_plan rows): a program family whose
            # transients the analytic floor consistently under-charges
            # scores its real HBM traffic.  Factor 1.0 (exact) with no
            # trained drift model — the empty-log bit-for-bit guarantee.
            hints = c.hints
            dfac = drift_factor(hbm_features(
                hints.get("arg_bytes", 0),
                hints.get("temp_bytes", 0),
                hints.get("out_bytes", 0),
                c.mesh_axes,
            ))
            if dfac != 1.0:
                hints = dict(hints)
                hints["temp_bytes"] = hints.get("temp_bytes", 0) * dfac
            rec.byte_drift = round(dfac, 4)
            raw = model.predict_seconds(hints)
            feats = plan_features(c.kind, c.mesh_axes, c.hints)
            factor, samples, source = calibrate(
                fingerprint, c.name, features=feats
            )
            rec.raw_seconds = raw
            rec.calibration = factor
            rec.samples = samples
            rec.calibration_source = source
            rec.features = feats
            rec.predicted_seconds = raw * factor
            if samples < MIN_TRAIN:
                trained = False
            survivors.append((c, rec))
        # 3. rank: within-margin candidates keep their prior order (the
        # tight margin only for measured-vs-measured pairs), floor pinned
        # last.  ``margin`` on the plan reports the factor the HEAD
        # comparison got.
        margin = TRAINED_MARGIN if trained and survivors else UNTRAINED_MARGIN
        body = [sr for sr in survivors if not sr[0].floor]
        floor = [sr for sr in survivors if sr[0].floor]
        ordered = _margin_order(body) + sorted(
            floor, key=lambda sr: sr[1].prior_rank
        )
        for i, (c, rec) in enumerate(ordered):
            rec.reason = (
                f"rank {i}: predicted {rec.predicted_seconds:.4g}s "
                f"(prior {rec.raw_seconds:.4g}s x calibration "
                f"{rec.calibration:.3g} [{rec.calibration_source}] from "
                f"{rec.samples} direct outcome(s))"
                + (" [floor: pinned last]" if c.floor else "")
            )
        # Pruned HAND candidates stay in the execution order at their hand
        # position (their cached analytic deny is handed to the ladder walk
        # — rejected for free, and the FitReport's denial ORDER matches the
        # hand contract exactly).  Pruned EXTRA candidates are dropped: the
        # search enumerated them, the placement table shows why they lost,
        # and the hand report's shape stays untouched.
        ranking: list[tuple] = list(ordered)
        by_name = {c.name: c for c in candidates}
        pruned_hand = [
            r for r in records if r.pruned and by_name[r.name].hand
        ]
        for rec in sorted(pruned_hand, key=lambda r: r.prior_rank):
            at = len(ranking)
            for i, (rc, _rrec) in enumerate(ranking):
                if rc.floor or (rc.hand and rc.prior_rank > rec.prior_rank):
                    at = i
                    break
            ranking.insert(at, (by_name[rec.name], rec))
        for i, (_c, rec) in enumerate(ranking):
            rec.rank = i
    plan = PlacementPlan(
        label=label,
        fingerprint=fingerprint,
        devices=device_fingerprint(),
        trained=trained,
        margin=margin if survivors else UNTRAINED_MARGIN,
        candidates=records,
        ranking=[rec.name for _, rec in ranking],
        search_seconds=time.perf_counter() - t0,
        analytic_plans={
            rec.name: analytic[rec.name] for rec in records if rec.pruned
        },
    )
    trace.instant(
        "autoshard_plan",
        label=label,
        fingerprint=fingerprint,
        ranking=plan.ranking,
        pruned=[r.name for r in records if r.pruned],
        trained=trained,
    )
    # The search itself is part of the metrics surface (ISSUE 11): how
    # many searches this process ran, how long they take, and whether the
    # cost model was trained — readable from one registry snapshot next
    # to the serving/ingest/fault groups.
    trace.metrics.inc("autoshard_searches")
    trace.metrics.observe("autoshard_search_seconds", plan.search_seconds)
    trace.metrics.gauge("autoshard_last_search_trained", 1.0 if trained else 0.0)
    _logger.info("%s", plan.summary())
    return plan


def will_search(plan_arg) -> bool:
    """Whether ``fit(plan=plan_arg)`` will run the placement search — the
    solvers' guard for skipping candidate-enumeration work (building a
    jax Mesh per device factorization) that a hand-ladder walk would
    discard unused."""
    return _resolve(plan_arg)[0]


def _resolve(plan_arg) -> tuple[bool, list | None]:
    """``fit(plan=...)`` semantics -> (search?, forced ranking names)."""
    if plan_arg is None:
        return enabled(), None
    if plan_arg is False:
        return False, None
    if plan_arg is True:
        return True, None
    if isinstance(plan_arg, PlacementPlan):
        return True, list(plan_arg.ranking)
    if isinstance(plan_arg, (list, tuple)):
        return True, [str(n) for n in plan_arg]
    raise TypeError(
        f"fit(plan=...) wants None/bool/PlacementPlan/name list, got "
        f"{type(plan_arg).__name__}"
    )


def run_search(
    label: str,
    candidates: Sequence[Candidate],
    report: "kmem.FitReport",
    *,
    fingerprint: str,
    plan=None,
    budget: int | None | object = kmem._UNSET,
    model: "kopt.CostModel | None" = None,
):
    """The solvers' one entry point: search (or honor the ``plan``
    override), then drive the RANKED candidate list through
    ``core.memory.run_ladder`` — the same per-tier compiled admission and
    one-plan-at-a-time OOM step-down contract the hand ladders obey, now
    over the searched order.  Attaches the finished :class:`PlacementPlan`
    record to ``report.placement``, appends outcomes to the plan log, and
    counts every step off the top-ranked plan under ``autoshard_stepdown``.
    """
    do_search, forced = _resolve(plan)
    report.fingerprint = fingerprint
    by_prior = sorted(candidates, key=lambda c: c.prior_rank)
    if not do_search:
        tiers = [
            kmem.Tier(c.name, c.plan, c.run)
            for c in by_prior
            if c.hand  # the hand ladder is exactly the hand candidates
        ]
        return kmem.run_ladder(label, tiers, report)

    placement = search(
        label, candidates, fingerprint=fingerprint, budget=budget, model=model
    )
    if forced is not None:
        known = {c.name for c in candidates}
        ranking = [n for n in forced if n in known]
        # anything the override did not name keeps its searched order
        ranking += [n for n in placement.ranking if n not in ranking]
        # the floor stays the backstop even under a forced ranking
        floors = [c.name for c in by_prior if c.floor and c.name in ranking]
        ranking = [n for n in ranking if n not in floors] + floors
        placement.ranking = ranking
        # Re-stamp the audit table to the order that will EXECUTE — the
        # searched rank/reason would otherwise contradict the replay.
        for rec in placement.candidates:
            rec.rank = None
        for i, name in enumerate(ranking):
            rec = placement.candidate(name)
            if rec is None:
                continue
            rec.rank = i
            if rec.predicted_seconds is not None:
                rec.reason = (
                    f"rank {i} (forced replay): predicted "
                    f"{rec.predicted_seconds:.4g}s (prior "
                    f"{rec.raw_seconds:.4g}s x calibration "
                    f"{rec.calibration:.3g} from {rec.samples} outcome(s))"
                )

    by_name = {c.name: c for c in candidates}
    measured: dict[str, float] = {}

    def wrap(c: Candidate) -> kmem.Tier:
        cached_deny = placement.analytic_plans.get(c.name)
        # A pruned candidate's walk "plan" IS the search's analytic deny —
        # denied for free, never compiled; the ladder records the denial
        # at its hand position like any preflight-denied tier.
        plan_fn = (
            (lambda: cached_deny) if cached_deny is not None else c.plan
        )

        def run(mplan):
            rec = placement.candidate(c.name)
            t0 = time.perf_counter()
            with trace.plan_span(
                f"plan:{c.name}",
                predicted_seconds=rec.predicted_seconds if rec else None,
                label=label,
                rank=rec.rank if rec else None,
                specs=spec_tag(rec.specs if rec else None),
            ):
                try:
                    out = c.run(mplan)
                    # Sync before reading the clock: a fused program's run
                    # returns async-dispatched arrays, so an unsynced
                    # measurement records ~0s dispatch time — garbage that
                    # would train the calibration model toward "free".
                    # The sync also surfaces an ASYNC runtime
                    # RESOURCE_EXHAUSTED here, inside the ladder's try,
                    # so it steps down counted instead of escaping at the
                    # caller's first use of the result.
                    _block_until_ready(out)
                except Exception:
                    measured[c.name] = time.perf_counter() - t0
                    raise
            measured[c.name] = time.perf_counter() - t0
            if kprof.enabled() and mplan is not None:
                # Audit the hand-derived flops hint against the compiled
                # program's own cost_analysis (ISSUE 14): single-device
                # candidates only — SPMD modules report per-device numbers
                # whose hint mapping is mesh-dependent, and a misleading
                # audit would be worse than none.  Mismatch beyond the
                # tolerance factor is counted, never silent.
                chips = 1
                for v in (c.mesh_axes or {}).values():
                    chips *= int(v)
                if chips == 1:
                    kprof.audit_flops(
                        f"{label}:{c.name}",
                        c.hints.get("flops"),
                        getattr(mplan, "compiled", None),
                    )
            return out

        return kmem.Tier(c.name, plan_fn, run)

    tiers = [wrap(by_name[n]) for n in placement.ranking if n in by_name]
    try:
        out = kmem.run_ladder(label, tiers, report)
    finally:
        _finish(placement, report, measured, fingerprint, label)
    return out


def _block_until_ready(out) -> None:
    """Best-effort sync on a tier run's result pytree (measurement
    honesty + async-OOM surfacing; a result that cannot sync — or no
    live backend — is not an error)."""
    try:
        import jax

        jax.block_until_ready(out)
    except Exception as e:  # noqa: BLE001 — only OOM matters here
        if kmem.is_oom_error(e):
            raise


def _finish(placement, report, measured, fp, label) -> None:
    """Post-run bookkeeping: predicted-vs-actual on the plan, outcome rows
    to the log, step-downs counted."""
    placement.chosen = report.chosen
    for name, secs in measured.items():
        rec = placement.candidate(name)
        if rec is None:
            continue
        rec.measured_seconds = secs
        # Only a genuine RESOURCE_EXHAUSTED step-down (run_ladder's
        # oom_retries) is a memory misprediction; a typed non-OOM failure
        # that propagated must not masquerade as one in the audit trail
        # or the plan log.
        if name == report.chosen:
            rec.outcome = "ok"
        elif name in report.oom_retries:
            rec.outcome = "oom"
        else:
            rec.outcome = "error"
        append_outcome({
            "fingerprint": fp,
            "label": label,
            "candidate": name,
            "predicted_seconds": rec.predicted_seconds,
            "raw_seconds": rec.raw_seconds,
            "measured_seconds": secs,
            "outcome": rec.outcome,
            "devices": placement.devices,
            "specs": rec.specs,
            # the cross-program training row: the NEXT process's
            # CalibrationModel regresses measured/raw on these.
            "features": rec.features,
            "ts": time.time(),
        })
    chosen_rec = (
        placement.candidate(report.chosen) if report.chosen else None
    )
    if chosen_rec is not None:
        placement.predicted_seconds = chosen_rec.predicted_seconds
        placement.measured_seconds = chosen_rec.measured_seconds
        if chosen_rec.predicted_seconds and chosen_rec.measured_seconds:
            placement.prediction_error = (
                chosen_rec.predicted_seconds / chosen_rec.measured_seconds
            )
    for name in report.oom_retries:
        if placement.candidate(name) is not None:
            counters.record(
                "autoshard_stepdown",
                f"{label}: ranked plan {name!r} died RESOURCE_EXHAUSTED at "
                "runtime — stepping down the searched ranking "
                f"(cost-model misprediction logged for {fp})",
            )
    report.placement = placement.record()
