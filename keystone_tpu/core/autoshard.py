"""Automated sharding/placement search: cost-model-ranked plans replace the
hand-enumerated ladders.

The degradation ladders (core.memory.run_ladder) encode placement as two
hand-written lists: fused -> stepwise -> host-staged on one device, full
mesh -> collapsed mesh -> single device across chips.  That is KeystoneML's
pre-optimizer posture — operator choices written down instead of searched.
This module is the whole-pipeline-optimizer treatment for PLACEMENT
(Automap and the Learned Cost Model placement paper, PAPERS.md): given a
solve's candidate executions — every (data, model) factorization of the
live device set (parallel.mesh.enumerate_mesh_shapes) x sharding spec per
operand (from the program's avals, :func:`spec_candidates`) x execution
strategy (fused / stepwise / host-staged) — the search

1. **prunes** candidates with the zero-cost analytic batch preflight
   (core.memory.plan_bytes / plan_batch — no compile; a denied plan is
   free to reject, and the full compiled admission still guards whatever
   the ladder later selects);
2. **scores** survivors with the shared cost model
   (core.optimize.CostModel): an analytic roofline prior over per-chip
   bytes / FLOPs / dispatches / collective volume, multiplied by a learned
   per-(program, candidate) calibration fitted to MEASURED outcomes from
   the persistent plan-outcome log (``~/.keystone_plans.jsonl``, keyed by
   program fingerprint) — the model improves across runs;
3. **ranks** with a confidence margin: candidates whose predicted costs
   are within one margin FACTOR of the cheapest remaining candidate keep
   their prior (hand-ladder) order (:data:`UNTRAINED_MARGIN` cold,
   :data:`TRAINED_MARGIN` for pairs where BOTH sides carry >=
   :data:`MIN_TRAIN` direct measurements) — an untrained prior never
   deviates from the proven default on noise, so a searched fit is
   bit-identical to the hand ladder until real measurements argue
   otherwise; the resilience floor is pinned last regardless of score;
4. **runs** the ranked list through the SAME ``run_ladder`` contract the
   hand ladders use — per-tier compiled admission at selection, runtime
   RESOURCE_EXHAUSTED steps down the RANKED list one plan at a time
   (counted ``autoshard_stepdown``), typed errors propagate — and lands
   the full candidate table, deny/score rationale, and predicted-vs-actual
   cost of the chosen plan in the :class:`PlacementPlan` attached to the
   solver's ``FitReport``.

``KEYSTONE_AUTOSHARD=0`` restores the hand ladders; ``fit(plan=...)``
overrides per call (``False`` hand, ``True`` force search, a
:class:`PlacementPlan` or name list replays a previous ranking).
``KEYSTONE_PLAN_LOG`` points the outcome log elsewhere (``off`` disables).
The log is read ONCE per process: outcomes appended during a run train the
NEXT process, so a ranking can never silently change between a baseline
and a comparison fit inside one process (the chaos bit-equality bar).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import time
from typing import Any, Callable, Sequence

import numpy as np

from . import memory as kmem
from . import optimize as kopt
from . import trace
from .resilience import counters

_logger = logging.getLogger("keystone_tpu.autoshard")

#: env var: "0"/"off"/"false" restores the hand ladders process-wide.
AUTOSHARD_ENV = "KEYSTONE_AUTOSHARD"

#: env var: plan-outcome log path; default ``~/.keystone_plans.jsonl``;
#: "0"/"off"/"none" disables persistence.
PLAN_LOG_ENV = "KEYSTONE_PLAN_LOG"
_DEFAULT_PLAN_LOG = "~/.keystone_plans.jsonl"

#: measurements per (fingerprint, candidate) before its calibration counts.
MIN_TRAIN = 3
#: cold-start ranking margin: an untrained analytic score must beat the
#: cheapest remaining candidate by this FACTOR before reordering past a
#: prior-earlier plan — the guarantee that a searched fit without
#: measurements reproduces the hand ladder's choice bit-for-bit.
UNTRAINED_MARGIN = 4.0
#: margin for a pair of candidates that BOTH carry >= MIN_TRAIN direct
#: measured outcomes — only like-for-like measured comparisons get the
#: tight margin; any pair with an unmeasured side keeps the cold one.
TRAINED_MARGIN = 1.15

#: bound on how much of the log one process will read back (newest wins).
_MAX_LOG_RECORDS = 50_000


def enabled() -> bool:
    """Search is the default; ``KEYSTONE_AUTOSHARD=0`` restores the hand
    ladders."""
    return os.environ.get(AUTOSHARD_ENV, "").strip().lower() not in (
        "0", "off", "false",
    )


# -- program fingerprints ------------------------------------------------------


def fingerprint(label: str, *parts) -> str:
    """Stable 16-hex-char fingerprint of a solve program's cost identity:
    the label plus whatever shapes/dtypes/statics/device description the
    caller folds in.  Same fingerprint => the plan log's measurements are
    comparable => same ranking under a fixed device set (the determinism
    contract the tests pin)."""
    blob = json.dumps([label, *map(str, parts)], sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def device_fingerprint(devices=None) -> str:
    """``'cpu x8'``-style description of the device set a plan assumed."""
    try:
        if devices is None:
            import jax

            devices = jax.devices()
        devices = list(devices)
        kind = getattr(devices[0], "device_kind", "unknown")
        return f"{kind} x{len(devices)}"
    except Exception:  # noqa: BLE001 — no backend yet
        return "unknown x0"


# -- sharding-spec enumeration from avals --------------------------------------


def spec_candidates(aval, mesh_shape: dict) -> list[dict]:
    """Candidate shardings for ONE operand aval under a (data, model) mesh
    shape — generated from the aval's dimensions, not a hand list: the data
    axis over any evenly-divisible dim, the model axis over any other
    evenly-divisible dim, and replicated (always legal).  Each entry
    carries the spec's per-chip bytes, the quantity the cost model charges.
    """
    shape = tuple(int(d) for d in aval.shape)
    itemsize = np.dtype(aval.dtype).itemsize
    total = int(np.prod(shape)) * itemsize if shape else itemsize
    out = [{"spec": "replicated", "per_chip_bytes": total}]
    d_sz = int(mesh_shape.get("data", 1))
    m_sz = int(mesh_shape.get("model", 1))
    for dim, n in enumerate(shape):
        if d_sz > 1 and n % d_sz == 0:
            out.append({
                "spec": f"data@dim{dim}",
                "per_chip_bytes": total // d_sz,
            })
        if m_sz > 1 and n % m_sz == 0:
            out.append({
                "spec": f"model@dim{dim}",
                "per_chip_bytes": total // m_sz,
            })
    return out


def best_spec(aval, mesh_shape: dict) -> dict:
    """The minimum-per-chip-bytes legal sharding for one aval — what the
    analytic byte accounting assumes a candidate mesh can achieve for a
    shardable operand (replicated when nothing divides)."""
    cands = spec_candidates(aval, mesh_shape)
    return min(cands, key=lambda c: (c["per_chip_bytes"], c["spec"]))


# -- the plan-outcome log ------------------------------------------------------


def plan_log_path() -> str | None:
    raw = os.environ.get(PLAN_LOG_ENV, "").strip()
    if raw.lower() in ("0", "off", "none"):
        return None
    return os.path.expanduser(raw or _DEFAULT_PLAN_LOG)


def hermetic_plan_log() -> str:
    """Point the plan-outcome log at a fresh throwaway file and forget any
    cached read.  For measurement/chaos drivers (bench sections,
    tools/chaos_run.py): their fixed-seed synthetic fits must neither
    TRAIN the operator's real log (three bench rounds would calibrate the
    bench fingerprints and start reordering the very ranking the driver
    asserts is hand-identical) nor evict real workload records from its
    bounded tail."""
    import tempfile

    path = os.path.join(
        tempfile.mkdtemp(prefix="keystone_plans_hermetic_"), "plans.jsonl"
    )
    os.environ[PLAN_LOG_ENV] = path
    clear_outcome_cache()
    return path


def append_outcome(record: dict) -> None:
    """Best-effort append of one plan outcome to the persistent log.  A
    broken log path degrades counted (``plan_log_write_failed``) — the
    solve's result never depends on telemetry landing."""
    path = plan_log_path()
    if path is None:
        return
    try:
        parent = os.path.dirname(os.path.abspath(path))
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(record) + "\n")
    except OSError as e:
        counters.record("plan_log_write_failed", f"{path}: {e}")


#: path -> parsed records, filled once per process (see module docstring:
#: in-process stability is what keeps baseline-vs-faulted comparisons
#: bit-equal; fresh measurements train the NEXT process).
_outcome_cache: dict[str, list] = {}


def load_outcomes(path: str | None = None) -> list[dict]:
    path = path if path is not None else plan_log_path()
    if path is None:
        return []
    cached = _outcome_cache.get(path)
    if cached is not None:
        return cached
    records: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # a torn tail line is not an error
    except OSError:
        records = []
    records = records[-_MAX_LOG_RECORDS:]
    _outcome_cache[path] = records
    return records


def clear_outcome_cache() -> None:
    """Test seam: forget the once-per-process log read."""
    _outcome_cache.clear()
    _ratio_cache.clear()


#: path -> ({(fingerprint, candidate): ratios}, {fingerprint: ratios}) —
#: one pass over the log per process instead of a rescan per candidate
#: (the search's O(candidates) calibration lookups must stay O(1) against
#: a log grown toward _MAX_LOG_RECORDS, or the scan itself would eat the
#: <5% search-overhead budget).
_ratio_cache: dict[str, tuple[dict, dict]] = {}


def _ratio_index(path: str | None) -> tuple[dict, dict]:
    key = path if path is not None else (plan_log_path() or "")
    cached = _ratio_cache.get(key)
    if cached is not None:
        return cached
    by_pair: dict = {}
    by_fp: dict = {}
    for r in load_outcomes(path):
        if not (
            r.get("outcome") == "ok"
            and r.get("predicted_seconds")
            and r.get("measured_seconds")
        ):
            continue
        ratio = r["measured_seconds"] / r["predicted_seconds"]
        fp = r.get("fingerprint")
        by_pair.setdefault((fp, r.get("candidate")), []).append(ratio)
        by_fp.setdefault(fp, []).append(ratio)
    _ratio_cache[key] = (by_pair, by_fp)
    return by_pair, by_fp


def calibration(fp: str, candidate: str, path: str | None = None) -> tuple[float, int]:
    """``(factor, direct_samples)`` for one (fingerprint, candidate) pair:
    the median measured/predicted ratio over the log's successful outcomes.

    Training is one-sided — only plans that actually RAN log outcomes — so
    below :data:`MIN_TRAIN` direct samples the factor falls back to the
    PROGRAM-level median (every candidate of the fingerprint pooled): a
    CONSTANT factor across all uncalibrated siblings, which shifts their
    absolute predictions toward honesty without ever reordering them.
    Without the fallback, the measured winner would absorb its real
    slowdown while unmeasured competitors kept optimistic raw priors, and
    the ranking would drift toward whatever never ran.  The returned
    sample count is the DIRECT count — it drives the per-pair trained
    margin, which a pooled fallback must not tighten."""
    by_pair, by_fp = _ratio_index(path)
    direct = by_pair.get((fp, candidate), ())
    if len(direct) >= MIN_TRAIN:
        return float(np.median(direct)), len(direct)
    pooled = by_fp.get(fp, ())
    if len(pooled) >= MIN_TRAIN:
        return float(np.median(pooled)), len(direct)
    return 1.0, len(direct)


# -- candidates and the plan record --------------------------------------------


@dataclasses.dataclass
class Candidate:
    """One executable placement: a mesh shape (or none) x execution
    strategy, with the lazy compiled preflight / run closures the ladder
    consumes and the analytic cost hints the search scores."""

    name: str
    kind: str  #: "fused_mesh" | "fused" | "stepwise" | "host_staged" | ...
    plan: Callable[[], "kmem.MemoryPlan"]
    run: Callable[["kmem.MemoryPlan"], Any]
    #: analytic per-chip cost hints (CostModel.predict_seconds keys) plus
    #: the prune figures plan_bytes charges (arg/temp/out/extra/resident).
    hints: dict = dataclasses.field(default_factory=dict)
    mesh_axes: dict | None = None
    prior_rank: int = 0  #: hand-ladder position (ties resolve to this)
    floor: bool = False  #: the resilience backstop — always ranked last
    hand: bool = True  #: hand-ladder member (its prunes land in FitReport)


@dataclasses.dataclass
class CandidateRecord:
    """One row of the plan's candidate table — the deny/score rationale."""

    name: str
    kind: str
    mesh: dict | None
    prior_rank: int
    pruned: bool
    reason: str  #: deny reason when pruned, score rationale otherwise
    predicted_seconds: float | None = None
    raw_seconds: float | None = None  #: analytic prior before calibration
    calibration: float = 1.0
    samples: int = 0  #: measured outcomes behind the calibration
    rank: int | None = None  #: position in the execution ranking
    measured_seconds: float | None = None  #: filled when this plan RAN
    outcome: str | None = None  #: "ok" | "oom" | "denied" after the run

    def record(self) -> dict:
        out = dataclasses.asdict(self)
        for k in ("predicted_seconds", "raw_seconds", "measured_seconds"):
            if out[k] is not None:
                out[k] = round(out[k], 6)
        out["calibration"] = round(self.calibration, 4)
        return out


@dataclasses.dataclass
class PlacementPlan:
    """The search's audit trail (FitReport's placement leg): every
    enumerated candidate with its deny/score rationale, the ranking that
    actually executed, and the chosen plan's predicted-vs-actual cost."""

    label: str
    fingerprint: str
    devices: str
    trained: bool
    margin: float
    candidates: list  #: list[CandidateRecord], prior order
    ranking: list  #: candidate names, execution order (floor last)
    search_seconds: float = 0.0
    chosen: str | None = None
    predicted_seconds: float | None = None
    measured_seconds: float | None = None
    prediction_error: float | None = None  #: predicted / measured
    #: name -> the zero-cost analytic MemoryPlan the batch preflight
    #: produced (pruned candidates hand it straight to the ladder walk —
    #: a pruned plan is denied for free, never re-planned or compiled).
    analytic_plans: dict = dataclasses.field(
        default_factory=dict, repr=False, compare=False
    )

    def candidate(self, name: str) -> CandidateRecord | None:
        for c in self.candidates:
            if c.name == name:
                return c
        return None

    def record(self) -> dict:
        return {
            "label": self.label,
            "fingerprint": self.fingerprint,
            "devices": self.devices,
            "trained": self.trained,
            "margin": self.margin,
            "search_seconds": round(self.search_seconds, 6),
            "ranking": list(self.ranking),
            "chosen": self.chosen,
            "predicted_seconds": (
                round(self.predicted_seconds, 6)
                if self.predicted_seconds is not None else None
            ),
            "measured_seconds": (
                round(self.measured_seconds, 6)
                if self.measured_seconds is not None else None
            ),
            "prediction_error": (
                round(self.prediction_error, 4)
                if self.prediction_error is not None else None
            ),
            "candidates": [c.record() for c in self.candidates],
        }

    def to_json(self) -> str:
        return json.dumps(self.record())

    def summary(self) -> str:
        s = (
            f"autoshard {self.label}[{self.fingerprint}]: "
            f"{len(self.ranking)}/{len(self.candidates)} candidates ranked"
            f" ({'trained' if self.trained else 'untrained'} margin "
            f"{self.margin}x), head={self.ranking[0] if self.ranking else None}"
        )
        if self.chosen is not None:
            s += f", chosen={self.chosen}"
        if self.prediction_error is not None:
            s += f", prediction_error={self.prediction_error:.2f}x"
        return s


# -- search + ranked execution -------------------------------------------------


def _margin_order(body: list) -> list:
    """Margin-aware selection order over ``(Candidate, CandidateRecord)``
    pairs: at each step, among the remaining candidates whose predicted
    cost is within the confidence margin of the CHEAPEST remaining one,
    the lowest prior (hand) rank wins.  Relative margins (not absolute
    buckets — two scores a hair apart must never split across a bucket
    edge and reorder) and per-pair trained-ness: the tight
    :data:`TRAINED_MARGIN` applies only when BOTH the candidate and the
    cheapest one carry >= :data:`MIN_TRAIN` direct measurements."""
    ordered: list = []
    remaining = sorted(body, key=lambda sr: sr[1].prior_rank)
    while remaining:
        best = min(remaining, key=lambda sr: (sr[1].predicted_seconds,
                                              sr[1].prior_rank))
        def margin(sr, best=best):
            both_trained = (
                sr[1].samples >= MIN_TRAIN and best[1].samples >= MIN_TRAIN
            )
            return TRAINED_MARGIN if both_trained else UNTRAINED_MARGIN

        pick = min(
            (
                sr for sr in remaining
                if sr[1].predicted_seconds
                <= best[1].predicted_seconds * margin(sr)
            ),
            key=lambda sr: sr[1].prior_rank,
        )
        ordered.append(pick)
        remaining.remove(pick)
    return ordered


def search(
    label: str,
    candidates: Sequence[Candidate],
    *,
    fingerprint: str,
    budget: int | None | object = kmem._UNSET,
    model: "kopt.CostModel | None" = None,
) -> PlacementPlan:
    """Enumerate -> prune -> score -> rank.  Pure decision pass: nothing is
    compiled and nothing runs — see :func:`run_search` for execution."""
    t0 = time.perf_counter()
    model = model if model is not None else kopt.CostModel.for_devices()
    records: list[CandidateRecord] = []
    survivors: list[tuple[Candidate, CandidateRecord]] = []
    with trace.span("autoshard.search", cat="plan", label=label):
        # 1. zero-cost batch preflight: analytic per-chip bytes vs budget.
        analytic = kmem.plan_batch([
            (
                c.name,
                lambda c=c: kmem.plan_bytes(
                    f"autoshard:{c.name}",
                    # LOWER bound of the compiled admission (see
                    # plan_bytes): donated/aliased argument bytes are
                    # credited out so the prune can never deny a plan the
                    # full preflight would admit.
                    argument_bytes=max(
                        0,
                        c.hints.get("arg_bytes", 0)
                        - c.hints.get("alias_bytes", 0),
                    ),
                    temp_bytes=c.hints.get("temp_bytes", 0),
                    extra_bytes=c.hints.get("extra_bytes", 0),
                    resident_bytes=c.hints.get("resident_bytes", 0),
                    budget=budget,
                ),
            )
            for c in candidates
        ])
        trained = True
        for c in candidates:
            mp = analytic[c.name]
            rec = CandidateRecord(
                name=c.name,
                kind=c.kind,
                mesh=dict(c.mesh_axes) if c.mesh_axes else None,
                prior_rank=c.prior_rank,
                pruned=not mp.admitted and not c.floor,
                reason=mp.reason,
            )
            records.append(rec)
            if rec.pruned:
                rec.outcome = "denied"
                continue
            # 2. score: analytic roofline prior x learned calibration.
            raw = model.predict_seconds(c.hints)
            factor, samples = calibration(fingerprint, c.name)
            rec.raw_seconds = raw
            rec.calibration = factor
            rec.samples = samples
            rec.predicted_seconds = raw * factor
            if samples < MIN_TRAIN:
                trained = False
            survivors.append((c, rec))
        # 3. rank: within-margin candidates keep their prior order (the
        # tight margin only for measured-vs-measured pairs), floor pinned
        # last.  ``margin`` on the plan reports the factor the HEAD
        # comparison got.
        margin = TRAINED_MARGIN if trained and survivors else UNTRAINED_MARGIN
        body = [sr for sr in survivors if not sr[0].floor]
        floor = [sr for sr in survivors if sr[0].floor]
        ordered = _margin_order(body) + sorted(
            floor, key=lambda sr: sr[1].prior_rank
        )
        for i, (c, rec) in enumerate(ordered):
            rec.reason = (
                f"rank {i}: predicted {rec.predicted_seconds:.4g}s "
                f"(prior {rec.raw_seconds:.4g}s x calibration "
                f"{rec.calibration:.3g} from {rec.samples} outcome(s))"
                + (" [floor: pinned last]" if c.floor else "")
            )
        # Pruned HAND candidates stay in the execution order at their hand
        # position (their cached analytic deny is handed to the ladder walk
        # — rejected for free, and the FitReport's denial ORDER matches the
        # hand contract exactly).  Pruned EXTRA candidates are dropped: the
        # search enumerated them, the placement table shows why they lost,
        # and the hand report's shape stays untouched.
        ranking: list[tuple] = list(ordered)
        by_name = {c.name: c for c in candidates}
        pruned_hand = [
            r for r in records if r.pruned and by_name[r.name].hand
        ]
        for rec in sorted(pruned_hand, key=lambda r: r.prior_rank):
            at = len(ranking)
            for i, (rc, _rrec) in enumerate(ranking):
                if rc.floor or (rc.hand and rc.prior_rank > rec.prior_rank):
                    at = i
                    break
            ranking.insert(at, (by_name[rec.name], rec))
        for i, (_c, rec) in enumerate(ranking):
            rec.rank = i
    plan = PlacementPlan(
        label=label,
        fingerprint=fingerprint,
        devices=device_fingerprint(),
        trained=trained,
        margin=margin if survivors else UNTRAINED_MARGIN,
        candidates=records,
        ranking=[rec.name for _, rec in ranking],
        search_seconds=time.perf_counter() - t0,
        analytic_plans={
            rec.name: analytic[rec.name] for rec in records if rec.pruned
        },
    )
    trace.instant(
        "autoshard_plan",
        label=label,
        fingerprint=fingerprint,
        ranking=plan.ranking,
        pruned=[r.name for r in records if r.pruned],
        trained=trained,
    )
    _logger.info("%s", plan.summary())
    return plan


def will_search(plan_arg) -> bool:
    """Whether ``fit(plan=plan_arg)`` will run the placement search — the
    solvers' guard for skipping candidate-enumeration work (building a
    jax Mesh per device factorization) that a hand-ladder walk would
    discard unused."""
    return _resolve(plan_arg)[0]


def _resolve(plan_arg) -> tuple[bool, list | None]:
    """``fit(plan=...)`` semantics -> (search?, forced ranking names)."""
    if plan_arg is None:
        return enabled(), None
    if plan_arg is False:
        return False, None
    if plan_arg is True:
        return True, None
    if isinstance(plan_arg, PlacementPlan):
        return True, list(plan_arg.ranking)
    if isinstance(plan_arg, (list, tuple)):
        return True, [str(n) for n in plan_arg]
    raise TypeError(
        f"fit(plan=...) wants None/bool/PlacementPlan/name list, got "
        f"{type(plan_arg).__name__}"
    )


def run_search(
    label: str,
    candidates: Sequence[Candidate],
    report: "kmem.FitReport",
    *,
    fingerprint: str,
    plan=None,
    budget: int | None | object = kmem._UNSET,
    model: "kopt.CostModel | None" = None,
):
    """The solvers' one entry point: search (or honor the ``plan``
    override), then drive the RANKED candidate list through
    ``core.memory.run_ladder`` — the same per-tier compiled admission and
    one-plan-at-a-time OOM step-down contract the hand ladders obey, now
    over the searched order.  Attaches the finished :class:`PlacementPlan`
    record to ``report.placement``, appends outcomes to the plan log, and
    counts every step off the top-ranked plan under ``autoshard_stepdown``.
    """
    do_search, forced = _resolve(plan)
    by_prior = sorted(candidates, key=lambda c: c.prior_rank)
    if not do_search:
        tiers = [
            kmem.Tier(c.name, c.plan, c.run)
            for c in by_prior
            if c.hand  # the hand ladder is exactly the hand candidates
        ]
        return kmem.run_ladder(label, tiers, report)

    placement = search(
        label, candidates, fingerprint=fingerprint, budget=budget, model=model
    )
    if forced is not None:
        known = {c.name for c in candidates}
        ranking = [n for n in forced if n in known]
        # anything the override did not name keeps its searched order
        ranking += [n for n in placement.ranking if n not in ranking]
        # the floor stays the backstop even under a forced ranking
        floors = [c.name for c in by_prior if c.floor and c.name in ranking]
        ranking = [n for n in ranking if n not in floors] + floors
        placement.ranking = ranking
        # Re-stamp the audit table to the order that will EXECUTE — the
        # searched rank/reason would otherwise contradict the replay.
        for rec in placement.candidates:
            rec.rank = None
        for i, name in enumerate(ranking):
            rec = placement.candidate(name)
            if rec is None:
                continue
            rec.rank = i
            if rec.predicted_seconds is not None:
                rec.reason = (
                    f"rank {i} (forced replay): predicted "
                    f"{rec.predicted_seconds:.4g}s (prior "
                    f"{rec.raw_seconds:.4g}s x calibration "
                    f"{rec.calibration:.3g} from {rec.samples} outcome(s))"
                )

    by_name = {c.name: c for c in candidates}
    measured: dict[str, float] = {}

    def wrap(c: Candidate) -> kmem.Tier:
        cached_deny = placement.analytic_plans.get(c.name)
        # A pruned candidate's walk "plan" IS the search's analytic deny —
        # denied for free, never compiled; the ladder records the denial
        # at its hand position like any preflight-denied tier.
        plan_fn = (
            (lambda: cached_deny) if cached_deny is not None else c.plan
        )

        def run(mplan):
            rec = placement.candidate(c.name)
            t0 = time.perf_counter()
            with trace.plan_span(
                f"plan:{c.name}",
                predicted_seconds=rec.predicted_seconds if rec else None,
                label=label,
                rank=rec.rank if rec else None,
            ):
                try:
                    out = c.run(mplan)
                except Exception:
                    measured[c.name] = time.perf_counter() - t0
                    raise
            measured[c.name] = time.perf_counter() - t0
            return out

        return kmem.Tier(c.name, plan_fn, run)

    tiers = [wrap(by_name[n]) for n in placement.ranking if n in by_name]
    try:
        out = kmem.run_ladder(label, tiers, report)
    finally:
        _finish(placement, report, measured, fingerprint, label)
    return out


def _finish(placement, report, measured, fp, label) -> None:
    """Post-run bookkeeping: predicted-vs-actual on the plan, outcome rows
    to the log, step-downs counted."""
    placement.chosen = report.chosen
    for name, secs in measured.items():
        rec = placement.candidate(name)
        if rec is None:
            continue
        rec.measured_seconds = secs
        # Only a genuine RESOURCE_EXHAUSTED step-down (run_ladder's
        # oom_retries) is a memory misprediction; a typed non-OOM failure
        # that propagated must not masquerade as one in the audit trail
        # or the plan log.
        if name == report.chosen:
            rec.outcome = "ok"
        elif name in report.oom_retries:
            rec.outcome = "oom"
        else:
            rec.outcome = "error"
        append_outcome({
            "fingerprint": fp,
            "label": label,
            "candidate": name,
            "predicted_seconds": rec.predicted_seconds,
            "measured_seconds": secs,
            "outcome": rec.outcome,
            "devices": placement.devices,
            "ts": time.time(),
        })
    chosen_rec = (
        placement.candidate(report.chosen) if report.chosen else None
    )
    if chosen_rec is not None:
        placement.predicted_seconds = chosen_rec.predicted_seconds
        placement.measured_seconds = chosen_rec.measured_seconds
        if chosen_rec.predicted_seconds and chosen_rec.measured_seconds:
            placement.prediction_error = (
                chosen_rec.predicted_seconds / chosen_rec.measured_seconds
            )
    for name in report.oom_retries:
        if placement.candidate(name) is not None:
            counters.record(
                "autoshard_stepdown",
                f"{label}: ranked plan {name!r} died RESOURCE_EXHAUSTED at "
                "runtime — stepping down the searched ranking "
                f"(cost-model misprediction logged for {fp})",
            )
    report.placement = placement.record()
