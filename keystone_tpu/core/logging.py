"""Logging trait analog (reference src/main/scala/pipelines/Logging.scala:8-67).

Python stdlib logging with the same convenience surface, plus a wall-clock
stage timer (the reference's ``"Pipeline took N s"`` lines,
MnistRandomFFT.scala:34,86-87) and ``jax.named_scope`` tagging so stages show
up in the JAX profiler — the Spark-UI ``RDD.setName`` analog.  The stage
timer is built ON the trace subsystem (core.trace): every timed stage is
also a structured span in the ``KEYSTONE_TRACE`` timeline.

As a library we never touch the root logger; workload entry points call
:func:`configure_logging` to get console output (level from the
``KEYSTONE_LOG_LEVEL`` env knob unless passed explicitly).
"""

from __future__ import annotations

import contextlib
import logging
import os
import time

import jax

from . import trace

_ROOT = logging.getLogger("keystone_tpu")
_ROOT.addHandler(logging.NullHandler())

#: env var: log level name ("DEBUG", "INFO", ...) or numeric level for
#: :func:`configure_logging` when the caller does not pass one.
LOG_LEVEL_ENV = "KEYSTONE_LOG_LEVEL"


def _env_level(default: int = logging.INFO) -> int:
    raw = os.environ.get(LOG_LEVEL_ENV, "").strip()
    if not raw:
        return default
    if raw.lstrip("-").isdigit():
        return int(raw)
    level = logging.getLevelName(raw.upper())
    if isinstance(level, int):
        return level
    raise ValueError(
        f"{LOG_LEVEL_ENV}={raw!r} is neither a level name "
        "(DEBUG/INFO/WARNING/ERROR/CRITICAL) nor a number"
    )


def configure_logging(level: int | None = None) -> None:
    """Attach a console handler to the keystone_tpu logger tree.
    Called by workload CLIs (never on import).  ``level`` defaults to the
    ``KEYSTONE_LOG_LEVEL`` env knob, then INFO."""
    if level is None:
        level = _env_level()
    if any(not isinstance(h, logging.NullHandler) for h in _ROOT.handlers):
        _ROOT.setLevel(level)
        return
    handler = logging.StreamHandler()
    handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
    )
    _ROOT.addHandler(handler)
    _ROOT.setLevel(level)


class Logging:
    """Mixin giving ``log_info`` etc. on a per-class logger under the
    keystone_tpu hierarchy."""

    @property
    def logger(self) -> logging.Logger:
        return logging.getLogger(f"keystone_tpu.{type(self).__name__}")

    def log_debug(self, msg, *args):
        self.logger.debug(msg, *args)

    def log_info(self, msg, *args):
        self.logger.info(msg, *args)

    def log_warning(self, msg, *args):
        self.logger.warning(msg, *args)

    def log_error(self, msg, *args):
        self.logger.error(msg, *args)


@contextlib.contextmanager
def stage_timer(name: str, logger: logging.Logger | None = None):
    """Time a pipeline stage: same ``"<name> took N s"`` log line and
    signature as ever, now ALSO a ``trace.span`` (cat ``stage``) so stage
    timings land in the ``KEYSTONE_TRACE`` timeline, plus the
    ``jax.named_scope`` tag for the JAX profiler."""
    logger = logger or _ROOT
    t0 = time.perf_counter()
    with trace.span(name, cat="stage"):
        with jax.named_scope(name):
            yield
    logger.info("%s took %.3f s", name, time.perf_counter() - t0)
