"""Logging trait analog (reference src/main/scala/pipelines/Logging.scala:8-67).

Python stdlib logging with the same convenience surface, plus a wall-clock
stage timer (the reference's ``"Pipeline took N s"`` lines,
MnistRandomFFT.scala:34,86-87) and ``jax.named_scope`` tagging so stages show
up in the JAX profiler — the Spark-UI ``RDD.setName`` analog.

As a library we never touch the root logger; workload entry points call
:func:`configure_logging` to get console output.
"""

from __future__ import annotations

import contextlib
import logging
import time

import jax

_ROOT = logging.getLogger("keystone_tpu")
_ROOT.addHandler(logging.NullHandler())


def configure_logging(level: int = logging.INFO) -> None:
    """Attach a console handler to the keystone_tpu logger tree.
    Called by workload CLIs (never on import)."""
    if any(not isinstance(h, logging.NullHandler) for h in _ROOT.handlers):
        _ROOT.setLevel(level)
        return
    handler = logging.StreamHandler()
    handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s")
    )
    _ROOT.addHandler(handler)
    _ROOT.setLevel(level)


class Logging:
    """Mixin giving ``log_info`` etc. on a per-class logger under the
    keystone_tpu hierarchy."""

    @property
    def logger(self) -> logging.Logger:
        return logging.getLogger(f"keystone_tpu.{type(self).__name__}")

    def log_debug(self, msg, *args):
        self.logger.debug(msg, *args)

    def log_info(self, msg, *args):
        self.logger.info(msg, *args)

    def log_warning(self, msg, *args):
        self.logger.warning(msg, *args)

    def log_error(self, msg, *args):
        self.logger.error(msg, *args)


@contextlib.contextmanager
def stage_timer(name: str, logger: logging.Logger | None = None):
    """Time a pipeline stage and tag it for the profiler."""
    logger = logger or _ROOT
    t0 = time.perf_counter()
    with jax.named_scope(name):
        yield
    logger.info("%s took %.3f s", name, time.perf_counter() - t0)
