"""Device cost attribution: the per-program MFU ledger, the HBM watermark
sampler, and triggered XLA profiler capture.

The host side of this system is observable (spans, SLO surface, flight
recorder); the DEVICE was a black box: XLA's ``cost_analysis`` was called
ad-hoc in two bench-only sites, the solvers' hand-derived ``flops`` hints
were never audited against the compiler, and ``plan_program``'s charged
bytes were never compared to what the device actually allocated.  This
module is the measured substrate that closes those gaps (and the one the
ROADMAP's learned placement cost model reads — PAPERS.md: Automap; Learned
Cost Model for Placement on Reconfigurable Dataflow Hardware):

* **Program ledger** — every compiled-program execution path
  (``run_ladder`` tiers, ``ServingEngine`` buckets, the fused
  device-decode+featurize dispatch) calls :func:`record_program` with its
  compiled executable and device-synced measured wall; the ledger joins
  ``cost_analysis()`` FLOPs/bytes with the wall into live per-program MFU
  and roofline position (``optimize.CostModel`` device rate tables),
  exported as ``profiler_*`` gauges in ``trace.metrics`` (Prometheus rides
  for free) and ``profiler.program`` trace instants, and aggregated into
  the bench ``profiler`` section via :func:`ledger_record`.
* **HBM watermark sampler** — a background thread polls
  ``device.memory_stats()`` every ``KEYSTONE_HBM_SAMPLE_MS`` and keeps
  per-:func:`phase` high-water marks; :func:`audit_plan` compares a
  phase's watermark against the ``plan_program`` charge — drift beyond
  ``KEYSTONE_PLAN_DRIFT_TOL`` is counted (``plan_drift``) and appended to
  the plan-outcome log as calibration evidence (``outcome:"hbm_drift"``
  rows ``core.autoshard.drift_rows`` feeds to the cross-program
  ``CalibrationModel``), closing the predict -> measure -> learn loop on
  the MEMORY side the way plan outcomes already close it on time.  A
  sampler crash is a counted degradation (``profiler_sampler_crash``),
  never a failed run — the chaos family ``profiler_crash`` enforces it.
* **Triggered XLA capture** — :func:`maybe_capture` opens a bounded
  ``jax.profiler`` trace window under ``KEYSTONE_XPROF_DIR`` (at most
  :data:`MAX_CAPTURES_PER_KIND` per kind per process, one window at a
  time, ``KEYSTONE_XPROF_WINDOW_S`` long), fired by an SLO burn-rate
  breach (``telemetry.SLOTracker``) or any postmortem-family fault;
  capture paths are linked from the flight-recorder dump.

Overhead discipline: :func:`enabled` is one module-flag/env check; with
the profiler OFF every hook in the execution paths is that single check
(the tier-1 suite pins an empty ledger and no sampler thread after a
profiled-shape run).  ON, the per-run cost is one cached cost-analysis
lookup + a dict update under a lock — the bench measures the serve-path
p99 overhead against a <= 5% bar.
"""

from __future__ import annotations

import contextlib
import logging
import math
import os
import re
import threading
import time
import weakref

from . import trace
from .resilience import counters

_logger = logging.getLogger("keystone_tpu.profiler")

#: env var: ``1`` turns the cost-attribution layer on (ledger + sampler).
PROFILER_ENV = "KEYSTONE_PROFILER"
#: env var: HBM watermark sampling period in milliseconds.
HBM_SAMPLE_ENV = "KEYSTONE_HBM_SAMPLE_MS"
#: env var: directory for triggered ``jax.profiler`` capture windows
#: (unset = capture disabled).
XPROF_DIR_ENV = "KEYSTONE_XPROF_DIR"
#: env var: seconds one triggered capture window stays open.
XPROF_WINDOW_ENV = "KEYSTONE_XPROF_WINDOW_S"
#: env var: relative tolerance before watermark-vs-charge drift is counted.
DRIFT_TOL_ENV = "KEYSTONE_PLAN_DRIFT_TOL"

DEFAULT_HBM_SAMPLE_MS = 50.0
DEFAULT_XPROF_WINDOW_S = 0.5
DEFAULT_DRIFT_TOL = 0.25

#: Per-kind capture cap per process: the first windows around a breach
#: carry the information; a fault storm must not fill a disk with xprof.
MAX_CAPTURES_PER_KIND = 2

#: The hand-derived solver ``flops`` hints are order-of-magnitude cost
#: hints, not exact op counts (XLA fuses, rematerializes, and counts
#: transcendentals its own way) — agreement within this FACTOR is a pass;
#: outside it the hint is misleading the cost model and the mismatch is
#: counted (``flops_hint_mismatch``), never silent.
FLOPS_AUDIT_TOL = 8.0

_NAME_RE = re.compile(r"[^a-zA-Z0-9_.-]")

_override: bool | None = None


def _env_flag(name: str) -> bool:
    return os.environ.get(name, "").strip().lower() in ("1", "true", "on", "yes")


def enabled() -> bool:
    """Is the cost-attribution layer on?  ``KEYSTONE_PROFILER=1`` or the
    programmatic :func:`profiled` override.  This is THE hot-path check —
    every hook in the execution paths is gated on it."""
    if _override is not None:
        return _override
    return _env_flag(PROFILER_ENV)


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        _logger.error("%s=%r is not a number — using %g", name, raw, default)
        return default


def drift_tol() -> float:
    return max(0.0, _env_float(DRIFT_TOL_ENV, DEFAULT_DRIFT_TOL))


# -- cost analysis (the ONE cost_analysis call site) ---------------------------

#: id(obj) -> (weakref(obj), cost dict).  Bounded (probe-style callers
#: walk many throwaway executables; the ledger must not pin them), and
#: identity-validated through the weakref: a recycled id after GC must
#: never serve another program's flops.
_cost_cache: dict[int, tuple] = {}
_COST_CACHE_MAX = 256


def _keep_ref(obj):
    try:
        return weakref.ref(obj)
    except TypeError:  # unweakreferenceable executables: hold it strong
        return lambda o=obj: o


def _cache_cost(key_obj, cost) -> None:
    if len(_cost_cache) >= _COST_CACHE_MAX:
        _cost_cache.pop(next(iter(_cost_cache)))
    _cost_cache[id(key_obj)] = (_keep_ref(key_obj), cost)


def _cached_cost(key_obj):
    cached = _cost_cache.get(id(key_obj))
    if cached is not None and cached[0]() is key_obj:
        return cached[1]
    return None


def program_cost(compiled) -> dict:
    """``cost_analysis()`` of one compiled executable as a plain dict:
    ``{"flops": float|None, "bytes_accessed": float|None}``.  The single
    place the raw XLA cost-analysis quirks live (list-wrapped analyses,
    missing keys, backends without the API) — bench and every profiler
    hook read through here instead of re-implementing the unwrap."""
    cached = _cached_cost(compiled)
    if cached is not None:
        return cached
    out: dict = {"flops": None, "bytes_accessed": None}
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0]
        out["flops"] = float(analysis.get("flops", 0.0)) or None
        out["bytes_accessed"] = (
            float(analysis.get("bytes accessed", 0.0)) or None
        )
    except Exception:  # noqa: BLE001 — cost analysis is advisory
        pass
    _cache_cost(compiled, out)
    return out


def cost_pair(compiled) -> tuple[float | None, float | None]:
    """``(flops, bytes_accessed)`` — the tuple shape bench always wanted."""
    c = program_cost(compiled)
    return c["flops"], c["bytes_accessed"]


def jit_cost(jitted_fn, *args, **kwargs) -> tuple[float | None, float | None]:
    """``(flops, bytes_accessed)`` of a jitted callable on ``args`` —
    lowering hits the jit cache, so a warm function is never traced or
    compiled a second time (the former ``bench.compiled_cost``)."""
    try:
        compiled = jitted_fn.lower(*args, **kwargs).compile()
    except Exception:  # noqa: BLE001 — advisory
        return None, None
    return cost_pair(compiled)


#: (id(key_obj), shape_key) -> (weakref(key_obj), (flops, bytes)).  The
#: streaming hot paths (StreamBatch.apply, fused_apply) attribute the
#: SAME program once per chunk — re-lowering per chunk just to re-derive
#: identical numbers would be real per-chunk overhead, so the pair is
#: memoized on a stable live object + shape key (identity-validated, like
#: the executable cache above).
_keyed_cost_cache: dict[tuple, tuple] = {}


def jit_cost_keyed(
    key_obj, shape_key, jitted_fn, *args, **kwargs
) -> tuple[float | None, float | None]:
    """:func:`jit_cost` memoized under ``(key_obj identity, shape_key)``
    — one lower per (program, shape), not one per dispatch."""
    key = (id(key_obj), shape_key)
    cached = _keyed_cost_cache.get(key)
    if cached is not None and cached[0]() is key_obj:
        return cached[1]
    cost = jit_cost(jitted_fn, *args, **kwargs)
    if len(_keyed_cost_cache) >= _COST_CACHE_MAX:
        _keyed_cost_cache.pop(next(iter(_keyed_cost_cache)))
    _keyed_cost_cache[key] = (_keep_ref(key_obj), cost)
    return cost


def attributed_call(label: str, shape_key, fn, *args):
    """``fn(*args)`` with ledger attribution: device-synced wall, the
    memoized per-(fn, shape) cost pair (when ``fn`` is a lowerable jit),
    one :func:`record_program` row under ``label``.  THE profiled-dispatch
    idiom for the streaming hot paths (``StreamBatch.apply``,
    ``jpeg_device.fused_apply``) — callers gate on :func:`enabled`, so
    this is never on the off path.  Syncing trades the caller's
    pipelining for measurement; values are unchanged."""
    t0 = time.perf_counter()
    out = fn(*args)
    wall = synced_wall(out, t0)
    fl, ba = (
        jit_cost_keyed(fn, shape_key, fn, *args)
        if hasattr(fn, "lower")
        else (None, None)
    )
    record_program(label, None, wall, flops=fl, bytes_accessed=ba)
    return out


# -- device rates --------------------------------------------------------------

_rates_cache: dict | None = None


def device_rates() -> dict:
    """``{"peak_flops", "hbm_gbps"}`` for the live platform — the
    ``optimize.CostModel`` rate tables, read once per process.  Unknown
    device kinds get the conservative defaults; only MFU's absolute scale
    depends on them, and cross-round comparisons (bench_diff) compare
    like against like."""
    global _rates_cache
    if _rates_cache is None:
        from . import optimize as kopt

        model = kopt.CostModel.for_devices()
        _rates_cache = {
            "peak_flops": model.peak_flops,
            "hbm_gbps": model.hbm_gbps,
        }
    return _rates_cache


# -- the program ledger --------------------------------------------------------


class _ProgramRow:
    """Aggregated cost attribution for one program label."""

    __slots__ = (
        "label", "runs", "wall_seconds", "flops", "bytes_accessed",
        "last_wall_seconds", "last_mfu", "last_hbm_gbps",
    )

    def __init__(self, label: str):
        self.label = label
        self.runs = 0
        self.wall_seconds = 0.0
        self.flops = 0.0
        self.bytes_accessed = 0.0
        self.last_wall_seconds = 0.0
        self.last_mfu: float | None = None
        self.last_hbm_gbps: float | None = None

    def record(self) -> dict:
        rates = device_rates()
        wall = self.wall_seconds
        flops_rate = self.flops / wall if wall > 0 and self.flops else None
        gbps = (
            self.bytes_accessed / wall / 1e9
            if wall > 0 and self.bytes_accessed
            else None
        )
        intensity = (
            self.flops / self.bytes_accessed if self.bytes_accessed else None
        )
        ridge = rates["peak_flops"] / (rates["hbm_gbps"] * 1e9)
        out = {
            "runs": self.runs,
            "wall_seconds": round(wall, 6),
            "flops": self.flops or None,
            "bytes_accessed": self.bytes_accessed or None,
            "mfu": (
                round(flops_rate / rates["peak_flops"], 6)
                if flops_rate
                else None
            ),
            "achieved_hbm_gbps": round(gbps, 3) if gbps else None,
            "intensity_flop_per_byte": (
                round(intensity, 3) if intensity else None
            ),
            "ridge_flop_per_byte": round(ridge, 3),
            # Roofline position: below the ridge intensity the program's
            # ceiling is HBM bandwidth, above it the MXU peak.
            "bound": (
                ("memory" if intensity < ridge else "compute")
                if intensity
                else None
            ),
            "last_wall_seconds": round(self.last_wall_seconds, 6),
        }
        return out


_ledger_lock = threading.Lock()
_ledger: dict[str, _ProgramRow] = {}
_LEDGER_MAX = 512


def record_program(
    label: str,
    compiled,
    wall_seconds: float,
    *,
    flops: float | None = None,
    bytes_accessed: float | None = None,
) -> dict | None:
    """Attribute one device-synced execution of ``compiled`` to the
    ledger: joins the program's ``cost_analysis()`` FLOPs/bytes (cached
    per executable; explicit overrides win) with ``wall_seconds`` into
    per-run MFU and achieved HBM bandwidth.  Returns the per-run numbers
    (None when the profiler is off).  Exported live as
    ``profiler_<label>_mfu`` / ``profiler_<label>_gbps`` gauges and a
    ``profiler.program`` trace instant."""
    if not enabled():
        return None
    if flops is None or bytes_accessed is None:
        cost = (
            program_cost(compiled)
            if compiled is not None
            else {"flops": None, "bytes_accessed": None}
        )
        flops = flops if flops is not None else cost["flops"]
        bytes_accessed = (
            bytes_accessed
            if bytes_accessed is not None
            else cost["bytes_accessed"]
        )
    rates = device_rates()
    wall = max(float(wall_seconds), 0.0)
    mfu = (
        flops / wall / rates["peak_flops"] if flops and wall > 0 else None
    )
    gbps = (
        bytes_accessed / wall / 1e9 if bytes_accessed and wall > 0 else None
    )
    with _ledger_lock:
        row = _ledger.get(label)
        if row is None:
            if len(_ledger) >= _LEDGER_MAX:
                _ledger.pop(next(iter(_ledger)))
            row = _ledger[label] = _ProgramRow(label)
        row.runs += 1
        row.wall_seconds += wall
        row.last_wall_seconds = wall
        if flops:
            row.flops += flops
        if bytes_accessed:
            row.bytes_accessed += bytes_accessed
        row.last_mfu = mfu
        row.last_hbm_gbps = gbps
    metric = _NAME_RE.sub("_", label)
    if mfu is not None:
        trace.metrics.gauge(f"profiler_{metric}_mfu", round(mfu, 6))
    if gbps is not None:
        trace.metrics.gauge(f"profiler_{metric}_gbps", round(gbps, 3))
    trace.metrics.inc("profiler_programs_recorded")
    out = {
        "label": label,
        "wall_seconds": round(wall, 6),
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "mfu": round(mfu, 6) if mfu is not None else None,
        "achieved_hbm_gbps": round(gbps, 3) if gbps is not None else None,
    }
    trace.instant("profiler.program", **out)
    return out


def ledger() -> dict:
    """Snapshot of the per-program rows (label -> aggregate record)."""
    with _ledger_lock:
        rows = list(_ledger.values())
    return {r.label: r.record() for r in rows}


def ledger_record() -> dict:
    """The bench ``profiler`` section: the ledger plus the device rates
    the MFU figures were computed against and the flops-audit table."""
    return {
        "rates": dict(device_rates()),
        "programs": ledger(),
        "flops_audits": flops_audits(),
        "captures": capture_paths(),
    }


def synced_wall(out, t0: float) -> float:
    """Honest wall seconds for a possibly-async result: block until the
    result pytree is ready, then measure from ``t0``.  A wall that omits
    the device-side completion would train the MFU ledger toward
    dispatch-time fantasy numbers."""
    try:
        import jax

        jax.block_until_ready(out)
    except Exception:  # noqa: BLE001 — an unsyncable result is not an error
        pass
    return time.perf_counter() - t0


# -- the hand-derived flops-hint audit -----------------------------------------

_audit_lock = threading.Lock()
_audits: dict[str, dict] = {}


def audit_flops(
    label: str,
    hint_flops: float | None,
    compiled,
    *,
    chips: int = 1,
    tol_factor: float = FLOPS_AUDIT_TOL,
) -> float | None:
    """Audit a solver's hand-derived per-chip ``flops`` hint against the
    compiled program's own ``cost_analysis``.  ``chips`` multiplies the
    per-chip hint back to module scope for mesh candidates.  Returns the
    hint/compiled ratio (None when either side is unknown); a ratio
    outside ``[1/tol_factor, tol_factor]`` is counted
    (``flops_hint_mismatch``) — a hint misleading the placement cost
    model by an order of magnitude must be visible, not silent."""
    if not enabled() or not hint_flops or compiled is None:
        return None
    measured = program_cost(compiled)["flops"]
    if not measured:
        return None
    ratio = float(hint_flops) * max(1, int(chips)) / measured
    ok = (1.0 / tol_factor) <= ratio <= tol_factor
    with _audit_lock:
        _audits[label] = {
            "hint_flops": float(hint_flops) * max(1, int(chips)),
            "compiled_flops": measured,
            "ratio": round(ratio, 4),
            "tol_factor": tol_factor,
            "ok": ok,
        }
    if not ok:
        counters.record(
            "flops_hint_mismatch",
            f"{label}: hand flops hint x{ratio:.3g} of compiled "
            f"cost_analysis (tolerance x{tol_factor}) — the cost model is "
            "being fed a misleading hint",
        )
    trace.instant(
        "profiler.flops_audit", label=label, ratio=round(ratio, 4), ok=ok
    )
    return ratio


def flops_audits() -> dict:
    """label -> the most recent audit row for it."""
    with _audit_lock:
        return {k: dict(v) for k, v in _audits.items()}


# -- the HBM watermark sampler -------------------------------------------------


class HbmSampler:
    """Background thread polling device ``memory_stats()`` bytes-in-use.

    Keeps a process-lifetime high-water mark plus one per live
    :func:`phase`; phase exit takes one synchronous sample so a phase
    shorter than the polling period still gets a watermark.  A backend
    that cannot report (CPU without allocator stats) disables the sampler
    after its first poll — watermarks are then ``None`` and every audit
    skips, never guesses.  A CRASH of the sampling thread is a counted
    degradation (``profiler_sampler_crash``): the run it was watching
    completes unprofiled, bit-equal to an unprofiled run (the
    ``profiler_crash`` chaos family's invariant)."""

    def __init__(
        self,
        interval_ms: float | None = None,
        stats_fn=None,
    ):
        self.interval_s = (
            interval_ms
            if interval_ms is not None
            else _env_float(HBM_SAMPLE_ENV, DEFAULT_HBM_SAMPLE_MS)
        ) / 1e3
        self.interval_s = max(self.interval_s, 1e-4)
        self._stats_fn = stats_fn or self._device_stats
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._global_peak = 0
        self._phase_peaks: dict[str, int] = {}
        self._active: dict[str, int] = {}  # phase -> refcount
        self.samples = 0
        self.crashed = False
        self.unavailable = False
        self._thread = threading.Thread(
            target=self._loop, name="keystone-hbm-sampler", daemon=True
        )

    @staticmethod
    def _device_stats() -> int | None:
        import jax

        try:
            stats = jax.devices()[0].memory_stats()
        except Exception:  # noqa: BLE001 — backends without stats
            return None
        if not stats:
            return None
        used = stats.get("bytes_in_use")
        return int(used) if used else None

    def start(self) -> "HbmSampler":
        self._thread.start()
        return self

    def _loop(self) -> None:
        try:
            while not self._stop.wait(self.interval_s):
                if not self.sample():
                    return
        except Exception as e:  # noqa: BLE001 — counted, never a failed run
            self.crashed = True
            counters.record(
                "profiler_sampler_crash",
                f"HBM watermark sampler died ({type(e).__name__}: {e}) — "
                "run continues unprofiled",
            )

    def sample(self) -> bool:
        """Take one sample.  Returns False when the backend cannot report
        (the sampler retires itself — polling an API that will never
        answer is pure overhead)."""
        used = self._stats_fn()
        if used is None:
            self.unavailable = True
            self._stop.set()
            return False
        with self._lock:
            self.samples += 1
            self._global_peak = max(self._global_peak, used)
            for name in self._active:
                self._phase_peaks[name] = max(
                    self._phase_peaks.get(name, 0), used
                )
        trace.metrics.gauge("profiler_hbm_bytes_in_use", used)
        trace.metrics.gauge("profiler_hbm_watermark_bytes", self._global_peak)
        return True

    def phase_enter(self, name: str) -> None:
        with self._lock:
            n = self._active.get(name, 0)
            if n == 0:
                # Fresh entry: the phase's watermark must describe THIS
                # occupancy, not a bigger run that used the same phase
                # name earlier in the process — a stale peak would read
                # as spurious drift against the current plan's charge
                # (and poison the hbm_drift calibration rows).
                self._phase_peaks.pop(name, None)
            self._active[name] = n + 1

    def phase_exit(self, name: str) -> None:
        # One synchronous sample on the way out: a phase shorter than the
        # polling period still records the bytes it was holding.
        if not (self._stop.is_set() or self.crashed):
            with contextlib.suppress(Exception):
                self.sample()
        with self._lock:
            n = self._active.get(name, 0) - 1
            if n <= 0:
                self._active.pop(name, None)
            else:
                self._active[name] = n

    def watermark(self, phase: str | None = None) -> int | None:
        """High-water mark bytes: a phase's (None until it was sampled at
        least once) or the process-lifetime peak."""
        with self._lock:
            if phase is not None:
                return self._phase_peaks.get(phase)
            return self._global_peak or None

    def stop(self, timeout: float = 2.0) -> None:
        self._stop.set()
        self._thread.join(timeout)

    def record(self) -> dict:
        with self._lock:
            return {
                "samples": self.samples,
                "interval_ms": round(self.interval_s * 1e3, 3),
                "global_watermark_bytes": self._global_peak or None,
                "phase_watermark_bytes": dict(self._phase_peaks),
                "crashed": self.crashed,
                "unavailable": self.unavailable,
            }


_sampler_lock = threading.Lock()
_sampler: HbmSampler | None = None


def ensure_sampler(
    interval_ms: float | None = None, stats_fn=None
) -> HbmSampler | None:
    """The process sampler, started on first use (None when the profiler
    is off).  ``stats_fn`` is the test/chaos seam — an injected stats
    source replaces the device poll."""
    if not enabled():
        return None
    global _sampler
    with _sampler_lock:
        if _sampler is None or (
            stats_fn is not None and _sampler._stats_fn is not stats_fn
        ):
            if _sampler is not None:
                _sampler.stop(0.5)
            _sampler = HbmSampler(
                interval_ms=interval_ms, stats_fn=stats_fn
            ).start()
        return _sampler


def sampler() -> HbmSampler | None:
    return _sampler


def stop_sampler() -> None:
    global _sampler
    with _sampler_lock:
        if _sampler is not None:
            _sampler.stop()
            _sampler = None


@contextlib.contextmanager
def phase(name: str):
    """Attribute HBM watermarks inside this block to ``name`` (the solver
    fits and serve batches declare themselves; nested phases each get
    their own watermark).  A no-op when the profiler is off."""
    s = ensure_sampler()
    if s is None:
        yield
        return
    s.phase_enter(name)
    try:
        yield
    finally:
        s.phase_exit(name)


def watermark(phase_name: str | None = None) -> int | None:
    s = _sampler
    return s.watermark(phase_name) if s is not None else None


def audit_plan(
    label: str,
    plan,
    *,
    phase_name: str | None = None,
    fingerprint: str | None = None,
    features: dict | None = None,
) -> dict | None:
    """Compare the watermark the sampler actually saw against what
    ``plan_program`` charged for the program that ran.  Drift beyond
    ``KEYSTONE_PLAN_DRIFT_TOL`` (relative, either direction) is counted
    (``plan_drift``) and the row is appended to the plan-outcome log as an
    ``outcome:"hbm_drift"`` record — the byte-side calibration evidence
    ``core.autoshard.drift_rows`` feeds to the cross-program
    :class:`~keystone_tpu.core.optimize.CalibrationModel`.  Returns the
    audit row, or None when either side is unknown (no sampler, backend
    without stats, unanalyzed plan) — skipped, never guessed."""
    if not enabled():
        return None
    charged = int(getattr(plan, "total_bytes", 0) or 0)
    if charged <= 0:
        return None
    # PHASE watermark only — the process-lifetime global peak describes
    # whatever ran biggest since import, and auditing a small plan
    # against it would manufacture drift.  No phase sample (sampler dead
    # or phase never entered) -> skipped, never guessed.
    wm = watermark(phase_name or label)
    if not wm:
        return None
    drift = wm / charged
    tol = drift_tol()
    drifted = abs(math.log(drift)) > math.log1p(tol)
    audit = {
        "label": label,
        "charged_bytes": charged,
        "watermark_bytes": int(wm),
        "drift_ratio": round(drift, 4),
        "tolerance": tol,
        "drifted": drifted,
    }
    if drifted:
        from . import memory as kmem

        counters.record(
            "plan_drift",
            f"{label}: device watermark {kmem.fmt_bytes(wm)} vs plan charge "
            f"{kmem.fmt_bytes(charged)} (x{drift:.3g}, tol ±{tol:.0%}) — "
            "the admission model drifted from the device",
        )
    trace.instant("plan_drift", **audit)
    trace.metrics.gauge(
        f"profiler_{_NAME_RE.sub('_', label)}_plan_drift", round(drift, 4)
    )
    # The calibration evidence: one row per audited run, read back by
    # autoshard.drift_rows() / the byte-drift CalibrationModel in the NEXT
    # process (same once-per-process read discipline as plan outcomes).
    from . import autoshard

    if features is None:
        # Byte-composition features straight off the audited plan — the
        # same vector shape the search's scoring side builds from hints
        # (autoshard.hbm_features), so train and predict agree.
        features = autoshard.hbm_features(
            getattr(plan, "argument_bytes", 0),
            getattr(plan, "temp_bytes", 0),
            getattr(plan, "output_bytes", 0),
            getattr(plan, "mesh_axes", None),
        )
    autoshard.append_outcome({
        "fingerprint": fingerprint or f"hbm:{label}",
        "label": label,
        "candidate": label,
        "outcome": "hbm_drift",
        "charged_bytes": charged,
        "watermark_bytes": int(wm),
        "drift_ratio": drift,
        "features": features,
        "ts": time.time(),
    })
    return audit


# -- triggered XLA capture -----------------------------------------------------

_capture_lock = threading.Lock()
_capture_counts: dict[str, int] = {}
_capture_paths: list[str] = []
_capture_active = False
_capture_timer: threading.Timer | None = None
#: monotonically increasing window id: a close callback only stops the
#: window it OPENED (cancel() cannot stop an already-running timer, so
#: without ownership a stale closer could truncate a newer window).
_capture_gen = 0


def _xprof_dir() -> str | None:
    raw = os.environ.get(XPROF_DIR_ENV, "").strip()
    return raw or None


def _start_trace(logdir: str) -> None:  # seam: tests patch this
    import jax

    jax.profiler.start_trace(logdir)


def _stop_trace() -> None:  # seam: tests patch this
    import jax

    jax.profiler.stop_trace()


def capture_paths() -> list[str]:
    """Directories of every capture window this process opened (linked
    from flight-recorder postmortem dumps)."""
    with _capture_lock:
        return list(_capture_paths)


def maybe_capture(kind: str, reason: str = "") -> str | None:
    """Open one bounded ``jax.profiler`` trace window for trigger
    ``kind`` if ``KEYSTONE_XPROF_DIR`` is set, no window is already open,
    and the per-kind cap (:data:`MAX_CAPTURES_PER_KIND`) has room.  The
    window closes itself after ``KEYSTONE_XPROF_WINDOW_S`` on a daemon
    timer.  Returns the capture directory or None.  Never raises and
    never counts through the fault ledger — a capture fired FROM the
    fault path must not re-enter it."""
    dump_dir = _xprof_dir()
    if dump_dir is None:
        return None
    global _capture_active, _capture_gen
    with _capture_lock:
        n = _capture_counts.get(kind, 0)
        if n >= MAX_CAPTURES_PER_KIND or _capture_active:
            return None
        _capture_counts[kind] = n + 1
        _capture_active = True
        _capture_gen += 1
        gen = _capture_gen
    path = os.path.join(
        dump_dir, f"xprof_{_NAME_RE.sub('_', kind)}_{os.getpid()}_{n}"
    )
    try:
        os.makedirs(path, exist_ok=True)
        _start_trace(path)
    except Exception:  # noqa: BLE001 — capture is advisory
        _logger.exception("xprof capture for %r failed to start", kind)
        with _capture_lock:
            _capture_active = False
            # Refund the budget: no window opened, so a transient start
            # failure must not burn the kind's cap for the process.
            _capture_counts[kind] = max(0, _capture_counts.get(kind, 1) - 1)
        return None

    def _close(gen: int = gen) -> None:
        global _capture_active, _capture_timer
        with _capture_lock:
            if gen != _capture_gen or not _capture_active:
                # A reset (or a newer window) took over since this timer
                # was armed — the window it owned is already closed, and
                # stopping here would truncate someone else's capture.
                return
            _capture_active = False
            _capture_timer = None
        try:
            _stop_trace()
        except Exception:  # noqa: BLE001
            _logger.exception("xprof capture stop failed")

    timer = threading.Timer(
        _env_float(XPROF_WINDOW_ENV, DEFAULT_XPROF_WINDOW_S), _close
    )
    timer.daemon = True
    timer.start()
    with _capture_lock:
        _capture_paths.append(path)
        _capture_timer = timer
    trace.metrics.inc("profiler_captures")
    trace.instant("xprof_capture", kind=kind, path=path, reason=reason)
    _logger.warning(
        "xprof capture window opened -> %s (trigger %s%s)",
        path, kind, f": {reason}" if reason else "",
    )
    return path


# -- lifecycle / test seams ----------------------------------------------------


def reset_state() -> None:
    """Test isolation: empty ledger/audits, forget capture caps, stop and
    drop the sampler, cancel any open capture window's timer (a stale
    timer firing later would stop a NEW window early — or call
    ``stop_trace`` with nothing open)."""
    stop_sampler()
    global _capture_active, _capture_timer, _capture_gen
    with _capture_lock:
        _capture_counts.clear()
        _capture_paths.clear()
        was_open = _capture_active
        _capture_active = False
        # Invalidate every armed closer: cancel() cannot stop one that
        # already started running, but the generation check makes a
        # stale closer a no-op instead of a truncation of whatever
        # window opens next.
        _capture_gen += 1
        timer, _capture_timer = _capture_timer, None
    if timer is not None:
        timer.cancel()
    if was_open:
        # The reset owns the open window now — close it (best effort) so
        # no trace session outlives the reset.
        with contextlib.suppress(Exception):
            _stop_trace()
    with _ledger_lock:
        _ledger.clear()
    with _audit_lock:
        _audits.clear()
    _cost_cache.clear()
    _keyed_cost_cache.clear()


@contextlib.contextmanager
def profiled(
    on: bool = True,
    *,
    interval_ms: float | None = None,
    stats_fn=None,
):
    """Programmatic enable/disable for benches and tests: overrides the
    env gate for the block, starts the sampler (with an optional injected
    stats source — the chaos harness's crash seam), and restores the
    previous state (sampler stopped) on exit."""
    global _override
    prev = _override
    _override = on
    try:
        if on:
            # Pre-warm the lazies the first attribution would otherwise
            # pay ON the hot path (rate-table import, jax.devices): the
            # steady-state overhead is the number the bench bounds.
            device_rates()
            ensure_sampler(interval_ms=interval_ms, stats_fn=stats_fn)
        yield
    finally:
        _override = prev
        if on:
            stop_sampler()
