"""Materialized snapshot cache: decoded (or featurized) stream chunks on
disk, keyed by content, so repeat epochs stream at IO speed.

tf.data's ``snapshot`` transformation (PAPERS.md) is the model: the first
pass over an input pipeline materializes its output to disk, and later
epochs — or later *runs* — read the materialization instead of re-running
the expensive upstream stages.  Here the expensive upstream stage is JPEG
decode (BENCH_r05: ~900 images/sec decode vs 15-17k images/sec device
featurize), so a snapshot turns the decode wall into a sequential-read
problem.

Layout: one ROOT directory (``KEYSTONE_SNAPSHOT_DIR``) holds any number of
snapshots, one subdirectory each, named by a prefix of the snapshot KEY —
a sha256 over everything that determines the chunk stream bit-for-bit:

* **tar identity** — basename, size, mtime_ns of every member tar;
* **decode config** — native-vs-PIL decoder (their IDCTs differ), the
  MIN_DIM reject floor;
* **chunk assembly** — the stream batch size (chunk layout depends on it);
* **mode** — ``decoded`` (f32 image chunks) or ``featurized`` (feature
  rows; the key then also folds in the fitted featurizer's checkpoint
  digest via :func:`featurizer_digest`, ``core.checkpoint`` idioms);
* **extra** — a caller-supplied string keying anything else that selects
  or transforms members (keep-filters, label-file identity).

Each snapshot directory holds ``chunk_NNNNN.npz`` shards (one per emitted
stream chunk: indices, member names, payload array) plus a ``snapshot.json``
manifest recording the full key and every shard's size + sha256.  Writes
are CRASH-SAFE: shards land in a ``.tmp-*`` sibling directory and one
atomic ``os.replace`` of the directory — after the manifest is written —
is the commit point.  A directory without a committed manifest is invisible
to readers and reaped by ``tools/snapshot_admin.py``.

Staleness and corruption are NEVER silent: a key mismatch is a counted
miss (``snapshot_stale`` when a committed snapshot for the same tars
exists under a different key), and every shard's bytes are re-hashed at
read time — a mismatch raises :class:`SnapshotCorrupt`, which
``core.ingest`` converts into a counted ``snapshot_fallback`` to live
decode (bit-equal by construction: the shards that DID validate were the
writer's exact chunk bytes).
"""

from __future__ import annotations

import dataclasses
import hashlib
import io
import json
import logging
import os
import shutil
import tempfile

import numpy as np

from . import trace
from .resilience import counters

_logger = logging.getLogger("keystone_tpu.snapshot")

FORMAT_NAME = "keystone-tpu-snapshot"
FORMAT_VERSION = 1
MANIFEST_NAME = "snapshot.json"
#: ``decoded`` — f32 pixel chunks exactly as the ring carried them;
#: ``featurized`` — [b, D] feature rows keyed by the fitted featurizer's
#: digest; ``device`` — DEVICE-FORMAT pixel shards: dtype-final f32,
#: batch dim padded to an 8-row sharding quantum capped at the stream
#: batch size, never compressed and never compacted — a warm epoch reads a
#: shard and hands the bytes straight to ``device_put`` with zero host
#: transform (the tf.data-snapshot idea taken to its device-native
#: conclusion).
MODES = ("decoded", "featurized", "device")

#: env vars (documented in README's KEYSTONE_* table)
SNAPSHOT_DIR_ENV = "KEYSTONE_SNAPSHOT_DIR"
SNAPSHOT_MODE_ENV = "KEYSTONE_SNAPSHOT_MODE"
SNAPSHOT_COMPRESS_ENV = "KEYSTONE_SNAPSHOT_COMPRESS"


class SnapshotError(RuntimeError):
    """Unusable snapshot root / manifest schema violation."""


class SnapshotCorrupt(SnapshotError):
    """A shard's bytes do not match the manifest (truncated/bit-flipped
    file, torn write) — the reader must FALL BACK, counted, never serve
    the bytes."""


def snapshot_dir_env() -> str | None:
    """Snapshot root: ``KEYSTONE_SNAPSHOT_DIR`` env or None (off)."""
    raw = os.environ.get(SNAPSHOT_DIR_ENV, "").strip()
    return raw or None


def snapshot_mode_env() -> str:
    """``KEYSTONE_SNAPSHOT_MODE``: ``decoded`` (default) or ``featurized``."""
    raw = os.environ.get(SNAPSHOT_MODE_ENV, "").strip() or "decoded"
    if raw not in MODES:
        raise ValueError(
            f"{SNAPSHOT_MODE_ENV}={raw!r} must be one of {MODES}"
        )
    return raw


def snapshot_compress_env() -> bool:
    """``KEYSTONE_SNAPSHOT_COMPRESS``: shard compression on the WRITE path
    (``np.savez_compressed``; default ON — decoded uint8 pixels deflate
    well and the warm path is shard-IO-bound, so smaller shards read
    faster).  ``0`` writes plain ``np.savez``.  A READ-side knob does not
    exist on purpose: ``np.load`` handles both formats transparently, so
    shards written under either setting — including every pre-knob
    snapshot — stay readable forever (the key does not fold compression
    in: the decoded BITS are identical either way)."""
    return os.environ.get(SNAPSHOT_COMPRESS_ENV, "").strip() != "0"


# -- keys ---------------------------------------------------------------------


def file_identity(path: str) -> dict:
    """(basename, size, mtime_ns) of one file — the cheap content proxy
    used for tars and label files.  Content-hashing multi-GB tars per run
    would cost a full read; size+mtime is the tf.data/make-style contract
    (touch the input, invalidate the cache)."""
    st = os.stat(path)
    return {
        "name": os.path.basename(path),
        "bytes": int(st.st_size),
        "mtime_ns": int(st.st_mtime_ns),
    }


def tar_identity(path: str) -> list:
    """Identity rows for the tar (or directory of tars) a stream reads —
    same file set as ``image_loaders._tar_files``."""
    from ..loaders.image_loaders import _tar_files

    return [file_identity(p) for p in _tar_files(path)]


def decode_config_record() -> dict:
    """Everything that changes decode OUTPUT BITS: which decoder runs
    (native libjpeg vs PIL differ in IDCT rounding) and the reject floor."""
    from ..loaders import native_decode
    from ..loaders.image_loaders import MIN_DIM

    return {
        "native_decode": bool(native_decode.available()),
        "min_dim": int(MIN_DIM),
    }


def featurizer_digest(obj) -> str:
    """sha256 of a fitted featurizer's checkpoint encoding — the
    ``core.checkpoint`` serialization (registered nodes / pipelines /
    containers of arrays), so any weight or registered-field change moves
    the digest and therefore the snapshot key.  Raises
    :class:`~.checkpoint.CheckpointError` for unserializable objects (a
    featurized snapshot of an un-checkpointable featurizer would be
    un-keyable — refuse rather than cache silently stale)."""
    from .checkpoint import CheckpointError, _Encoder

    class _DigestEncoder(_Encoder):
        # A digest needs stable key material, not a reconstructible
        # artifact: dtype-likes the checkpoint schema refuses (e.g. the
        # jnp.bfloat16 scalar-meta a compute_dtype field holds) hash by
        # their dtype name; everything else still refuses.
        def encode(self, v, where):
            try:
                return super().encode(v, where)
            except CheckpointError:
                try:
                    return {"t": "py", "v": f"dtype:{np.dtype(v).name}"}
                except TypeError:
                    pass
                raise

    enc = _DigestEncoder()
    root = enc.encode(obj, "featurizer")
    buf = io.BytesIO()
    np.savez(buf, **enc.arrays)
    h = hashlib.sha256()
    h.update(json.dumps(root, sort_keys=True).encode())
    h.update(buf.getvalue())
    return h.hexdigest()


def snapshot_key(
    tar_path: str,
    *,
    batch_size: int,
    mode: str = "decoded",
    extra: str | None = None,
    featurizer: str | None = None,
) -> str:
    """The content hash naming one snapshot.  ``featurizer`` is the
    :func:`featurizer_digest` of the fitted featurizer (required when
    ``mode='featurized'`` — decoded pixels don't depend on any model,
    features do)."""
    if mode not in MODES:
        raise ValueError(f"snapshot mode {mode!r} must be one of {MODES}")
    if mode == "featurized" and featurizer is None:
        raise ValueError(
            "featurized snapshots need featurizer= (the fitted featurizer's "
            "digest) — without it a refit would silently reuse stale features"
        )
    doc = {
        "format": FORMAT_NAME,
        "version": FORMAT_VERSION,
        "tar": tar_identity(tar_path),
        "decode": decode_config_record(),
        "batch_size": int(batch_size),
        "mode": mode,
        "extra": extra,
        "featurizer": featurizer,
    }
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True).encode()
    ).hexdigest()


def _dir_for(root: str, key: str) -> str:
    return os.path.join(root, f"snap-{key[:16]}")


# -- writer -------------------------------------------------------------------


class SnapshotWriter:
    """Accumulate chunk shards, then :meth:`commit` atomically.

    Shards are written into a ``.tmp-*`` sibling of the final directory;
    the manifest (with per-shard size + sha256) is written LAST and the
    whole directory renamed into place in one ``os.replace`` — a crash at
    any earlier point leaves only an uncommitted temp directory that
    readers never see.  :meth:`abort` removes the temp directory (early
    consumer exit must not commit a partial snapshot)."""

    def __init__(
        self,
        root: str,
        key: str,
        *,
        mode: str,
        meta: dict | None = None,
        compress: bool | None = None,
    ):
        if mode not in MODES:
            raise ValueError(f"snapshot mode {mode!r} must be one of {MODES}")
        os.makedirs(root, exist_ok=True)
        self._root = root
        self._key = key
        self._mode = mode
        # device-format shards are NEVER compressed (warm reads must be
        # straight IO into H2D, not an inflate pass) — forced here so the
        # manifest's compress field tells the truth too
        self._compress = mode != "device" and (
            snapshot_compress_env() if compress is None else bool(compress)
        )
        self._meta = dict(meta or {})
        self._final = _dir_for(root, key)
        self._tmp = tempfile.mkdtemp(
            prefix=f".tmp-{key[:16]}-", dir=root
        )
        self._chunks: list[dict] = []
        self._images = 0
        self._done = False

    def add_chunk(
        self, index: int, indices, names, payload, *, pad_to: int | None = None
    ) -> None:
        """Write one stream chunk as a shard.  ``payload`` is the decoded
        [b, H, W, C] host batch (mode=decoded), the [b, D] feature rows
        (mode=featurized), or the dtype-final pixel batch (mode=device —
        ``pad_to`` pads the batch dim to the stream batch size with zero
        rows and records the ``valid`` count, so every warm shard is a
        fixed-shape, sharding-ready buffer)."""
        if self._done:
            raise SnapshotError("snapshot writer already committed/aborted")
        payload = np.asarray(payload)
        extra = {}
        if self._mode == "device":
            # dtype-final: the bytes on disk ARE the bytes device_put
            # consumes on the warm epoch — no cast, no compaction.  The
            # batch dim pads up to an 8-row sharding quantum (divisible
            # across typical data-parallel axes), CAPPED at the stream
            # batch size — padding a lone remainder chunk all the way to
            # a large batch size would multiply its shard bytes for no
            # layout benefit (the reader slices to ``valid`` anyway).
            payload = np.ascontiguousarray(payload, np.float32)
            valid = int(payload.shape[0])
            target = valid
            if pad_to is not None and pad_to > valid:
                target = min(int(pad_to), -(-valid // 8) * 8)
            if target > valid:
                payload = np.concatenate(
                    [
                        payload,
                        np.zeros(
                            (target - valid,) + payload.shape[1:],
                            payload.dtype,
                        ),
                    ]
                )
            extra["valid"] = np.asarray(valid, np.int64)
        if payload.dtype == np.float32 and self._mode == "decoded":
            # Decoded pixels are integral f32 straight off uint8 JPEG
            # samples — store them as uint8 (4x less shard IO, the whole
            # point of the cache) ONLY when the round trip is bit-exact.
            # Featurized rows are essentially never integral, so the
            # probe (two full passes + a temporary) is skipped by mode
            # rather than paid per chunk on the hot featurize path.
            u8 = payload.astype(np.uint8)
            if np.array_equal(payload, u8.astype(np.float32)):
                extra["payload_cast"] = np.asarray("float32")
                payload = u8
        buf = io.BytesIO()
        # Write-path-only choice: np.load reads both formats transparently,
        # so compressed and plain shards coexist (old snapshots stay
        # readable, and the shard sha256 below covers whichever bytes were
        # written).  Device-format shards are NEVER compressed: a warm
        # epoch's read must be memory-bandwidth IO straight into H2D, not
        # an inflate pass (that would be a host transform).
        save = np.savez_compressed if self._compress else np.savez
        save(
            buf,
            indices=np.asarray(indices, np.int64),
            names=np.asarray(list(names)),
            payload=payload,
            **extra,
        )
        data = buf.getvalue()
        fname = f"chunk_{len(self._chunks):05d}.npz"
        # image count = the VALID rows (== indices), never pad rows
        n_images = int(np.asarray(indices).shape[0])
        with trace.io_span(
            "snapshot.write_shard", len(data), cat="snapshot",
            file=fname, images=n_images,
        ):
            with open(os.path.join(self._tmp, fname), "wb") as fh:
                fh.write(data)
        self._chunks.append(
            {
                "index": int(index),
                "file": fname,
                "bytes": len(data),
                "sha256": hashlib.sha256(data).hexdigest(),
                "images": n_images,
                "shape": list(payload.shape),
                "compressed": self._compress,
                "payload_bytes": int(payload.nbytes),
            }
        )
        self._images += n_images

    def commit(self) -> str:
        """Write the manifest and rename the directory into place.
        Returns the committed snapshot path."""
        if self._done:
            raise SnapshotError("snapshot writer already committed/aborted")
        manifest = {
            "format": FORMAT_NAME,
            "version": FORMAT_VERSION,
            "key": self._key,
            "mode": self._mode,
            "images": self._images,
            "compress": self._compress,
            "chunks": self._chunks,
            "meta": self._meta,
        }
        with open(os.path.join(self._tmp, MANIFEST_NAME), "w") as fh:
            json.dump(manifest, fh, indent=1)
            fh.flush()
            os.fsync(fh.fileno())
        # Replace any previous snapshot under the same key (a corrupt one
        # being rewritten by the fallback pass): remove-then-rename — the
        # reader tolerates the tiny absent window (it falls back to live
        # decode, counted), and the rename itself is atomic.
        if os.path.isdir(self._final):
            shutil.rmtree(self._final, ignore_errors=True)
        os.replace(self._tmp, self._final)
        self._done = True
        _logger.info(
            "snapshot committed: %s (%d chunks, %d images, mode=%s)",
            self._final, len(self._chunks), self._images, self._mode,
        )
        trace.instant(
            "snapshot_commit",
            path=self._final, chunks=len(self._chunks), images=self._images,
        )
        return self._final

    def abort(self) -> None:
        """Drop the uncommitted shards (idempotent)."""
        if not self._done:
            shutil.rmtree(self._tmp, ignore_errors=True)
            self._done = True


# -- reader -------------------------------------------------------------------


@dataclasses.dataclass
class Snapshot:
    """One committed snapshot (validated manifest; shards validated lazily
    per read)."""

    path: str
    manifest: dict

    @property
    def key(self) -> str:
        return self.manifest["key"]

    @property
    def mode(self) -> str:
        return self.manifest["mode"]

    @property
    def images(self) -> int:
        return int(self.manifest.get("images", 0))

    def iter_chunks(self):
        """Yield ``(entry, arrays)`` per shard in write order, verifying
        each shard's size and sha256 over the exact bytes parsed — a
        mismatch raises :class:`SnapshotCorrupt` (the caller counts the
        fallback)."""
        for entry in self.manifest["chunks"]:
            fpath = os.path.join(self.path, entry["file"])
            try:
                with trace.io_span(
                    "snapshot.read_shard", entry["bytes"], cat="snapshot",
                    file=entry["file"],
                ):
                    with open(fpath, "rb") as fh:
                        data = fh.read()
            except OSError as e:
                raise SnapshotCorrupt(
                    f"{fpath}: unreadable shard ({e})"
                ) from e
            if (
                len(data) != entry["bytes"]
                or hashlib.sha256(data).hexdigest() != entry["sha256"]
            ):
                raise SnapshotCorrupt(
                    f"{fpath}: shard bytes do not match the manifest "
                    "(truncated or bit-flipped)"
                )
            try:
                with np.load(io.BytesIO(data), allow_pickle=False) as zf:
                    arrays = {k: zf[k] for k in zf.files}
            except (ValueError, OSError, KeyError) as e:
                raise SnapshotCorrupt(f"{fpath}: unparsable shard ({e})") from e
            if not {"indices", "names", "payload"} <= set(arrays):
                raise SnapshotCorrupt(
                    f"{fpath}: shard missing required arrays "
                    f"(has {sorted(arrays)})"
                )
            cast = arrays.pop("payload_cast", None)
            if cast is not None:
                # Reverse the writer's lossless uint8 compaction.
                arrays["payload"] = arrays["payload"].astype(str(cast))
            yield entry, arrays


def _read_manifest(path: str) -> dict | None:
    try:
        with open(os.path.join(path, MANIFEST_NAME)) as fh:
            manifest = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None
    if (
        manifest.get("format") != FORMAT_NAME
        or manifest.get("version") != FORMAT_VERSION
        or not isinstance(manifest.get("chunks"), list)
        or not isinstance(manifest.get("key"), str)
    ):
        return None
    return manifest


def lookup(
    root: str, key: str, *, tar_path: str | None = None,
    mode: str = "decoded",
) -> tuple[Snapshot | None, str]:
    """Find the committed snapshot for ``key`` under ``root``.

    Returns ``(snapshot, "hit")``, ``(None, "stale")`` when a committed
    SAME-MODE snapshot for the same tar basenames exists under a
    different key (the input or config moved — the caller counts
    ``snapshot_stale``; a different-mode snapshot was never a candidate
    for this key and must not read as staleness), or ``(None, "miss")``.
    """
    if not os.path.isdir(root):
        return None, "miss"
    path = _dir_for(root, key)
    manifest = _read_manifest(path) if os.path.isdir(path) else None
    if manifest is not None and manifest.get("key") == key:
        return Snapshot(path, manifest), "hit"
    if tar_path is not None:
        # Manifest-only scan: this runs on every cold stream start, so it
        # must not pay list_snapshots' per-shard stat accounting just to
        # classify stale-vs-miss.
        want = sorted(r["name"] for r in tar_identity(tar_path))
        for name in sorted(os.listdir(root)):
            if not name.startswith("snap-"):
                continue
            manifest = _read_manifest(os.path.join(root, name))
            if (
                manifest is not None
                and manifest.get("mode") == mode
                and sorted(
                    r.get("name", "")
                    for r in manifest.get("meta", {}).get("tar", [])
                )
                == want
            ):
                return None, "stale"
    return None, "miss"


def list_snapshots(root: str) -> list:
    """Inventory of everything under a snapshot root — committed snapshots
    (with manifest summary + validity) AND uncommitted ``.tmp-*`` leftovers
    (crash debris the admin tool can reap)."""
    out = []
    if not os.path.isdir(root):
        return out
    for name in sorted(os.listdir(root)):
        path = os.path.join(root, name)
        if not os.path.isdir(path):
            continue
        if name.startswith(".tmp-"):
            out.append(
                {
                    "dir": name,
                    "committed": False,
                    "valid": False,
                    "reason": "uncommitted temp directory (crashed or "
                    "in-progress write)",
                    "bytes": _dir_bytes(path),
                }
            )
            continue
        manifest = _read_manifest(path)
        if manifest is None:
            out.append(
                {
                    "dir": name,
                    "committed": False,
                    "valid": False,
                    "reason": "missing/invalid manifest",
                    "bytes": _dir_bytes(path),
                }
            )
            continue
        rec = {
            "dir": name,
            "committed": True,
            "key": manifest["key"],
            "mode": manifest["mode"],
            "images": manifest.get("images", 0),
            "chunks": len(manifest["chunks"]),
            "bytes": _dir_bytes(path),
            "tar_names": sorted(
                r.get("name", "")
                for r in manifest.get("meta", {}).get("tar", [])
            ),
            # Recorded chunking (the ingest tee writes both): lets the
            # admin tool recompute a snapshot's EXACT key for staleness
            # classification instead of probing guessed batch sizes.
            "batch_size": manifest.get("meta", {}).get("batch_size"),
            "extra": manifest.get("meta", {}).get("extra"),
            "valid": True,
            "reason": "ok",
        }
        out.append(rec)
    return out


def validate(root: str, key_prefix: str) -> list:
    """Full shard validation (size + sha256) of one snapshot — the admin
    ``inspect`` operation.  Returns a list of violations (empty = clean)."""
    matches = [
        d
        for d in os.listdir(root)
        if d.startswith("snap-") and d[5:].startswith(key_prefix[:16])
    ] if os.path.isdir(root) else []
    if not matches:
        return [f"no snapshot directory matching key prefix {key_prefix!r}"]
    problems = []
    for d in matches:
        path = os.path.join(root, d)
        manifest = _read_manifest(path)
        if manifest is None:
            problems.append(f"{d}: missing/invalid manifest")
            continue
        snap = Snapshot(path, manifest)
        try:
            for _entry, _arrays in snap.iter_chunks():
                pass
        except SnapshotCorrupt as e:
            problems.append(str(e))
    return problems


def evict(
    root: str,
    *,
    key_prefix: str | None = None,
    temps: bool = False,
    names: list | None = None,
) -> list:
    """Remove snapshot directories: those matching ``key_prefix`` (>= 4
    chars — a shorter prefix could match everything), uncommitted temp
    leftovers (``temps=True``), and/or exact directory ``names`` (the
    invalid-manifest case, where no key exists to match on).  Returns
    removed names."""
    if key_prefix is not None and len(key_prefix) < 4:
        raise ValueError(
            f"evict key_prefix {key_prefix!r} is shorter than 4 characters "
            "— a near-empty prefix would match every snapshot"
        )
    removed = []
    if not os.path.isdir(root):
        return removed
    wanted = set(names or ())
    for name in sorted(os.listdir(root)):
        path = os.path.join(root, name)
        if not os.path.isdir(path):
            continue
        kill = name in wanted
        if temps and name.startswith(".tmp-"):
            kill = True
        if (
            key_prefix is not None
            and name.startswith("snap-")
            and name[5:].startswith(key_prefix[:16])
        ):
            kill = True
        if kill:
            shutil.rmtree(path, ignore_errors=True)
            removed.append(name)
            counters.record("snapshot_evicted", name)
    return removed


def _dir_bytes(path: str) -> int:
    total = 0
    for entry in os.scandir(path):
        if entry.is_file():
            total += entry.stat().st_size
    return total
