"""Unified tracing & metrics: structured spans, a process-wide metrics
registry, and Chrome-trace/JSONL exporters.

KeystoneML's cost-based optimizer decides caching/materialization from
*measured per-node profiles* (time + output size, PipelineRuntimeEstimator);
tf.data lives on built-in per-stage metrics feeding autotuning.  Neither is
possible while timing/counters are scattered across ``stage_timer``,
``resilience.counters``, ``FitReport``, and ad-hoc ring stats with no shared
schema.  This module is that shared substrate:

* :func:`span` — a thread-safe context manager producing nested structured
  spans: wall time, thread id, nesting depth/parent, arbitrary JSON-able
  attributes (bytes/shape/dtype), optional device-sync time
  (``sp.sync(value)`` runs ``jax.block_until_ready`` and records the
  synced duration).  When tracing is disabled ``span()`` returns a shared
  no-op singleton — no allocation, no lock, one attribute check.
* :data:`metrics` — the process-wide registry unifying **counters**,
  **gauges**, and **histograms** behind one API, with an atomic
  :meth:`Metrics.snapshot`.  ``resilience.counters`` (the fault ledger)
  rides along as an adopted group, so one snapshot captures both.
* :func:`instant` — point events (admission decisions, fault counts) that
  land in the same timeline as spans.
* Exporters: **Chrome trace_event JSON** (loads in Perfetto / chrome://
  tracing; the default for ``*.json`` paths) and a **JSONL event log**
  (``*.jsonl``).  Enable with ``KEYSTONE_TRACE=out.json`` (checked once at
  import; the file is written at process exit) or programmatically with
  :func:`enable` / a workload's ``--trace`` flag.
* **Flight recorder** — a bounded ring of the most recent events that runs
  even with tracing DISABLED (``KEYSTONE_FLIGHT_DEPTH``, 0 disables): a
  fault that fires in an untraced production process still has its last
  moments on record, and ``core.telemetry`` dumps the ring as a postmortem
  JSON when a typed fault is counted.  The ring is a fixed-capacity deque
  — old events fall off the back, retained memory is bounded and constant
  once warm.

Overhead discipline: with tracing AND the flight ring off the path is a
module-state check returning a cached null object; with only the ring on,
each finished span is one small dict append into a bounded deque (the
tier-1 suite asserts no retained allocation growth once the ring is warm),
and the bench acceptance bound is < 2% on ``stage_ops`` with tracing off.
Enabled, each finished span is one dict append under a lock (bounded at
:data:`MAX_EVENTS`; overflow is counted, never unbounded).
"""

from __future__ import annotations

import atexit
import collections
import contextlib
import json
import logging
import os
import tempfile
import threading
import time

_logger = logging.getLogger("keystone_tpu.trace")

#: env var: path of the trace file to write at process exit ("out.json" for
#: Chrome trace_event JSON viewable in Perfetto, "out.jsonl" for JSONL).
TRACE_ENV = "KEYSTONE_TRACE"

#: env var: flight-recorder ring depth (events retained with tracing off);
#: ``0`` disables the ring entirely.
FLIGHT_ENV = "KEYSTONE_FLIGHT_DEPTH"

#: Default flight-ring depth: enough to hold the last few micro-batches of
#: serving lifecycle events around a fault, small enough that the retained
#: footprint (~a few hundred KB of dicts) is production-invisible.
DEFAULT_FLIGHT_DEPTH = 512

#: Hard cap on buffered events — a runaway span loop degrades to a counted
#: drop (``metrics`` counter ``trace_events_dropped``, plus a drop field in
#: both export formats), never unbounded RAM.
MAX_EVENTS = 1_000_000

_EPOCH = time.perf_counter()  # ts origin: microseconds since module import

# getpid() is a real syscall on every call (Python does not cache it), and
# on sandboxed kernels it measures ~10us — per EVENT that would dwarf the
# event itself.  Cached once; refreshed after fork so a forked child's
# events carry ITS pid.
_PID = os.getpid()


def _refresh_pid() -> None:
    global _PID
    _PID = os.getpid()


if hasattr(os, "register_at_fork"):  # pragma: no branch
    os.register_at_fork(after_in_child=_refresh_pid)

_lock = threading.Lock()
_events: list = []
_dropped = 0
#: Bumped by reset(): a span that outlives the buffer it was opened in
#: (e.g. an abandoned decoder thread finishing after a per-schedule
#: chaos reset) must not leak into the NEXT buffer with a stale tid.
_epoch = 0
_enabled = False
_path: str | None = None
_tids: dict[int, int] = {}  # threading.get_ident() -> small sequential tid
_tid_metas: dict[int, dict] = {}  # tid -> its thread_name metadata event
_tids_in_buffer: set = set()  # tids whose metadata reached _events
_tls = threading.local()  # per-thread span stack (nesting/parents)
_atexit_registered = False

# -- the always-on flight recorder ring.  Deliberately separate from the
# trace buffer: it records even when tracing is disabled, it is bounded by
# construction (deque maxlen — old events fall off), and it is never
# exported unless a postmortem asks for it (core.telemetry).
_flight_lock = threading.Lock()
_flight: collections.deque | None = None


def _parse_flight_depth() -> int:
    raw = os.environ.get(FLIGHT_ENV, "").strip()
    if not raw:
        return DEFAULT_FLIGHT_DEPTH
    try:
        depth = int(raw)
    except ValueError:
        _logger.error(
            "%s=%r is not an integer — flight recorder at default depth %d",
            FLIGHT_ENV, raw, DEFAULT_FLIGHT_DEPTH,
        )
        return DEFAULT_FLIGHT_DEPTH
    return max(0, depth)


def _now_us() -> float:
    return (time.perf_counter() - _EPOCH) * 1e6


def now_us() -> float:
    """The trace clock: microseconds since this module's import — the
    ``ts`` origin every span/instant uses.  Public for the wire protocol's
    clock-offset handshake (core.wire ``T_CLOCK``): two processes exchange
    their trace clocks so ``tools/trace_view.py --stitch`` can align a
    client's timeline with the server's."""
    return _now_us()


def _tid() -> int:
    """Small sequential id for the calling thread; first sight also emits
    the Chrome ``thread_name`` metadata event so Perfetto labels lanes."""
    ident = threading.get_ident()
    tid = _tids.get(ident)
    if tid is None:
        meta = None
        with _lock:
            tid = _tids.get(ident)
            if tid is None:
                tid = len(_tids)
                _tids[ident] = tid
                meta = {
                    "ph": "M",
                    "name": "thread_name",
                    "pid": _PID,
                    "tid": tid,
                    "args": {"name": threading.current_thread().name},
                }
                # Cached even when tracing is off: a thread first seen in
                # flight-only mode must still get its Perfetto lane label
                # if tracing is enabled later (enable() re-emits these).
                _tid_metas[tid] = meta
                if _enabled:
                    _events.append(meta)
                    _tids_in_buffer.add(tid)
        if meta is not None and _flight is not None:
            with _flight_lock:
                if _flight is not None:
                    _flight.append(meta)
    return tid


def _stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _record(event: dict) -> None:
    global _dropped
    with _lock:
        if len(_events) >= MAX_EVENTS:
            _dropped += 1
            overflow = True
        else:
            _events.append(event)
            overflow = False
    if overflow:
        # Counted OUTSIDE the trace lock (metrics has its own) so the
        # truncation shows up in every metrics snapshot, not just the
        # exporters' drop fields.
        metrics.inc("trace_events_dropped")


def _emit(event: dict) -> None:
    """Route one finished event: into the flight ring (always, when the
    ring is on) and into the trace buffer (only when tracing is enabled)."""
    if _flight is not None:
        with _flight_lock:
            if _flight is not None:
                _flight.append(event)
    if _enabled:
        _record(event)


class _NullSpan:
    """The disabled-mode span: a shared, allocation-free no-op."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self

    def sync(self, value):
        return value


_NULL = _NullSpan()


class Span:
    """One live span (use via ``with trace.span(...) as sp``)."""

    __slots__ = (
        "name", "cat", "attrs", "t0", "_tid", "_depth", "_parent", "_epoch"
    )

    def __init__(self, name: str, cat: str, attrs: dict):
        self.name = name
        self.cat = cat
        self.attrs = attrs
        self.t0 = 0.0
        self._tid = 0
        self._depth = 0
        self._parent = None
        self._epoch = 0

    def __enter__(self):
        stack = _stack()
        self._depth = len(stack)
        self._parent = stack[-1].name if stack else None
        stack.append(self)
        self._tid = _tid()
        self._epoch = _epoch
        self.t0 = _now_us()
        return self

    def __exit__(self, etype, exc, tb):
        t1 = _now_us()
        stack = _stack()
        if stack and stack[-1] is self:
            stack.pop()
        elif self in stack:  # exited out of order (generator close) — heal
            stack.remove(self)
        if self._epoch != _epoch:
            # The buffer this span was opened in was reset (per-schedule
            # chaos traces): a straggler from an abandoned thread must not
            # land in the NEXT trace with a stale tid.
            return False
        args = dict(self.attrs)
        args["depth"] = self._depth
        if self._parent is not None:
            args["parent"] = self._parent
        if etype is not None:
            if issubclass(etype, GeneratorExit):
                # A generator-hosted span (ingest.consume) is closed — not
                # failed — when the consumer stops early or raises outside
                # the generator frame; naming GeneratorExit as the error
                # would mask the consumer's real failure, which lands on
                # whatever span wraps the consumer code.
                args["aborted"] = True
            else:
                # Typed-error spans are never silent: the failure rides in
                # the span itself, matchable against the fault counters.
                args["error"] = etype.__name__
        _emit(
            {
                "ph": "X",
                "name": self.name,
                "cat": self.cat,
                "ts": self.t0,
                "dur": max(t1 - self.t0, 0.0),
                "pid": _PID,
                "tid": self._tid,
                "args": args,
            }
        )
        return False

    def set(self, **attrs) -> "Span":
        """Attach attributes (bytes, shapes, reports) to the span."""
        self.attrs.update(attrs)
        return self

    def sync(self, value):
        """``jax.block_until_ready(value)`` and record the device-sync
        time (span start -> sync completion) as ``sync_us``.  Returns
        ``value`` so call sites stay expression-shaped."""
        import jax

        value = jax.block_until_ready(value)
        self.attrs["sync_us"] = round(_now_us() - self.t0, 1)
        return value


def span(name: str, cat: str = "span", **attrs):
    """Open a structured span.  With tracing AND the flight ring both off
    this returns a shared no-op — the hot-path cost is two module-state
    checks; with only the flight ring on, the finished span lands in the
    bounded ring and nowhere else."""
    if not _enabled and _flight is None:
        return _NULL
    return Span(name, cat, attrs)


class _IOSpan(Span):
    """A span over a byte-moving operation (snapshot shard IO, shared-memory
    IPC): records ``bytes`` up front and derives ``mb_per_s`` at exit, so
    the trace answers "was this transfer bandwidth-bound?" without
    cross-referencing durations by hand."""

    __slots__ = ()

    def __exit__(self, etype, exc, tb):
        dur_s = (_now_us() - self.t0) / 1e6
        nbytes = self.attrs.get("bytes", 0)
        if dur_s > 0 and nbytes:
            self.attrs["mb_per_s"] = round(nbytes / dur_s / 1e6, 1)
        return super().__exit__(etype, exc, tb)


def io_span(name: str, nbytes: int, cat: str = "io", **attrs):
    """Span for an IO/IPC transfer of ``nbytes`` — like :func:`span`, plus
    achieved-bandwidth accounting (``bytes`` + ``mb_per_s`` attrs)."""
    if not _enabled and _flight is None:
        return _NULL
    attrs["bytes"] = int(nbytes)
    return _IOSpan(name, cat, attrs)


class _PlanSpan(Span):
    """A span over work the placement search PREDICTED a cost for
    (core.autoshard): records ``predicted_s`` (and optionally
    ``predicted_bytes``) up front and derives ``measured_s`` plus the
    predicted/measured ratio ``prediction_error`` at exit — the trace
    answers "how wrong was the cost model on the plan it chose?" without
    cross-referencing the plan log by hand."""

    __slots__ = ()

    def __exit__(self, etype, exc, tb):
        measured = (_now_us() - self.t0) / 1e6
        self.attrs["measured_s"] = round(measured, 6)
        predicted = self.attrs.get("predicted_s")
        if predicted and measured > 0:
            self.attrs["prediction_error"] = round(predicted / measured, 4)
        return super().__exit__(etype, exc, tb)


def plan_span(
    name: str,
    predicted_seconds: float | None = None,
    predicted_bytes: int | None = None,
    cat: str = "plan",
    **attrs,
):
    """Span for a placement-plan choice: like :func:`span`, plus
    predicted-vs-measured cost accounting (``predicted_s`` /
    ``measured_s`` / ``prediction_error`` attrs)."""
    if not _enabled and _flight is None:
        return _NULL
    if predicted_seconds is not None:
        attrs["predicted_s"] = round(float(predicted_seconds), 6)
    if predicted_bytes is not None:
        attrs["predicted_bytes"] = int(predicted_bytes)
    return _PlanSpan(name, cat, attrs)


def instant(name: str, **attrs) -> None:
    """Point event (admission decision, fault count) on the current
    thread's timeline.

    No epoch guard, deliberately (unlike spans): an instant is wholly
    inside the CURRENT buffer's lifetime — a straggler thread firing one
    after a reset() records an event that really happened now, and the
    matching counter increment lands in the same window's delta, so the
    chaos verifier's counted-fault -> trace-event pairing stays
    consistent.  A span, by contrast, opened before the reset would carry
    a stale tid/interval, which is why Span.__exit__ drops it."""
    if not _enabled and _flight is None:
        return
    _emit(
        {
            "ph": "i",
            "s": "t",
            "name": name,
            "cat": "instant",
            "ts": _now_us(),
            "pid": _PID,
            "tid": _tid(),
            "args": attrs,
        }
    )


def enabled() -> bool:
    return _enabled


def enable(path: str) -> None:
    """Turn tracing on, writing to ``path`` at :func:`flush` / process
    exit.  ``*.jsonl`` selects the JSONL event log; anything else writes
    Chrome trace_event JSON (Perfetto-loadable)."""
    global _enabled, _path, _atexit_registered
    # Fail fast on an unwritable destination: flush() runs at the END of a
    # (possibly hours-long) run — discovering a missing directory there
    # would lose the whole trace.
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    if not os.access(parent, os.W_OK):
        raise PermissionError(f"trace path directory {parent!r} not writable")
    with _lock:
        _path = path
        _enabled = True
        # Threads first registered while tracing was off (flight-only
        # mode) have cached thread_name metas — emit them now so their
        # lanes are labeled in the flushed trace.
        for tid, meta in _tid_metas.items():
            if tid not in _tids_in_buffer:
                _events.append(meta)
                _tids_in_buffer.add(tid)
        if not _atexit_registered:
            atexit.register(_flush_at_exit)
            _atexit_registered = True
    _logger.info("tracing enabled -> %s", path)


def disable() -> None:
    """Stop recording (buffered events are kept until :func:`reset`)."""
    global _enabled
    _enabled = False


def reset() -> None:
    """Drop every buffered event AND the flight ring (test isolation;
    per-schedule traces).  Spans still open when reset is called belong to
    the OLD buffer and are discarded at their exit (epoch check), never
    recorded into the new one."""
    global _dropped, _epoch
    with _lock:
        _events.clear()
        _tids.clear()
        _tid_metas.clear()
        _tids_in_buffer.clear()
        _dropped = 0
        _epoch += 1
    flight_reset()


def events() -> list:
    """Snapshot (copy) of the buffered events."""
    with _lock:
        return list(_events)


# -- flight recorder ----------------------------------------------------------


def flight_depth() -> int:
    """Current flight-ring capacity (0 = disabled)."""
    with _flight_lock:
        return _flight.maxlen if _flight is not None else 0


def set_flight_depth(depth: int) -> None:
    """Resize the flight ring to ``depth`` events (0 disables it).  The
    most recent events that still fit are kept."""
    global _flight
    with _flight_lock:
        if depth <= 0:
            _flight = None
            return
        kept = list(_flight)[-depth:] if _flight is not None else []
        _flight = collections.deque(kept, maxlen=int(depth))


def flight_events() -> list:
    """Snapshot (copy) of the flight ring, oldest first."""
    with _flight_lock:
        return list(_flight) if _flight is not None else []


def flight_reset() -> None:
    """Drop the flight ring's contents (capacity unchanged)."""
    with _flight_lock:
        if _flight is not None:
            _flight.clear()


def atomic_write(path: str, write) -> None:
    """Crash-safe text-file write (the ``core.checkpoint`` idiom, shared
    by the trace flush and the telemetry exporters): ``write(f)`` runs on
    a same-directory temp file which is fsynced and atomically renamed
    into place — a crash mid-write leaves the previous file intact; a
    failed write unlinks its temp.  The result gets world-readable 0644
    perms (mkstemp's private 0600 would hide exported metrics/traces from
    scraper users)."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=os.path.basename(path) + ".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            write(f)
            f.flush()
            os.fsync(f.fileno())
        os.chmod(tmp, 0o644)
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


def flush(path: str | None = None) -> str | None:
    """Write the buffered events to ``path`` (default: the enabled path).
    Chrome format for ``*.json``, JSONL for ``*.jsonl``.  Returns the
    path written, or None when there is nowhere to write.  Crash-safe via
    :func:`atomic_write` — never a truncated Perfetto JSON."""
    path = path or _path
    if path is None:
        return None
    with _lock:
        evs = list(_events)
        dropped = _dropped

    def write(f) -> None:
        if path.endswith(".jsonl"):
            for ev in evs:
                f.write(json.dumps(ev) + "\n")
            if dropped:
                # Truncation must be visible in THIS format too, not
                # just the Chrome JSON's otherData field.
                f.write(
                    json.dumps(
                        {"ph": "M", "name": "dropped_events",
                         "pid": _PID, "tid": 0,
                         "args": {"count": dropped}}
                    ) + "\n"
                )
        else:
            json.dump(
                {
                    "traceEvents": evs,
                    "displayTimeUnit": "ms",
                    "otherData": {
                        "producer": "keystone_tpu.core.trace",
                        "dropped_events": dropped,
                    },
                },
                f,
            )

    atomic_write(path, write)
    return path


def _flush_at_exit() -> None:
    try:
        if _path is not None and (_events or _enabled):
            flush()
    except Exception:  # noqa: BLE001 — never break interpreter shutdown
        _logger.exception("trace flush at exit failed")


# -- metrics registry ---------------------------------------------------------


class _Hist:
    """Streaming histogram: count/sum/min/max plus a bounded sample window
    for percentiles (last :data:`_HIST_WINDOW` observations)."""

    _WINDOW = 1024
    __slots__ = ("count", "total", "min", "max", "samples")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self.samples: collections.deque = collections.deque(maxlen=self._WINDOW)

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += value
        self.min = min(self.min, value)
        self.max = max(self.max, value)
        self.samples.append(value)

    def summary(self) -> dict:
        if not self.count:
            return {"count": 0}
        s = sorted(self.samples)
        pick = lambda q: s[min(len(s) - 1, int(q * len(s)))]  # noqa: E731
        return {
            "count": self.count,
            "mean": self.total / self.count,
            "min": self.min,
            "max": self.max,
            "p50": pick(0.50),
            "p90": pick(0.90),
            "p99": pick(0.99),
        }


class Metrics:
    """Thread-safe registry of counters, gauges, and histograms.

    External counter groups with their own lock (``resilience.counters``)
    are *adopted*: they keep their API and storage, and ride along in
    every :meth:`snapshot` under their group name — one snapshot captures
    the whole process's metrics surface atomically per group.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, int] = {}
        self._gauges: dict[str, float] = {}
        self._hists: dict[str, _Hist] = {}
        self._groups: dict[str, object] = {}

    # counters ---------------------------------------------------------------
    def inc(self, name: str, n: int = 1) -> int:
        with self._lock:
            self._counters[name] = total = self._counters.get(name, 0) + n
        return total

    def get(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def counters(self) -> dict:
        with self._lock:
            return dict(self._counters)

    # gauges -----------------------------------------------------------------
    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def gauge_value(self, name: str, default: float | None = None) -> float | None:
        """Read one gauge back (controllers — the ingest autotuner — consume
        the same live registry the exporters snapshot)."""
        with self._lock:
            return self._gauges.get(name, default)

    # histograms -------------------------------------------------------------
    def observe(self, name: str, value: float) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = _Hist()
            h.observe(value)

    def hist_windows(self) -> dict:
        """Raw per-histogram sample windows (count/total/min/max plus the
        bounded sample deque as a list) — the wire payload the fleet
        observability plane ships so FLEET percentiles come from pooled
        samples, not averaged per-host percentiles (core.fleetobs)."""
        with self._lock:
            return {
                k: {
                    "count": h.count,
                    "total": h.total,
                    "min": h.min,
                    "max": h.max,
                    "samples": list(h.samples),
                }
                for k, h in self._hists.items()
                if h.count
            }

    # groups -----------------------------------------------------------------
    def adopt(self, name: str, group) -> None:
        """Register an external counter group (must expose
        ``snapshot(reset=False) -> dict``) under ``name``."""
        with self._lock:
            self._groups[name] = group

    # snapshot ---------------------------------------------------------------
    def snapshot(self, reset: bool = False) -> dict:
        """Atomic copy of every counter/gauge/histogram (and each adopted
        group via ITS own atomic snapshot).  ``reset=True`` clears the
        registry under the same lock — read-then-reset can never lose a
        concurrent increment."""
        with self._lock:
            out = {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.summary() for k, h in self._hists.items()},
            }
            groups = dict(self._groups)
            if reset:
                self._counters.clear()
                self._gauges.clear()
                self._hists.clear()
        for name, group in groups.items():
            out[name] = group.snapshot(reset=reset)
        return out

    def reset(self) -> None:
        self.snapshot(reset=True)


#: Process-wide registry.  ``resilience.counters`` adopts itself in as the
#: "faults" group, so ``metrics.snapshot()`` captures perf metrics and the
#: fault ledger in one record (bench embeds exactly this).
metrics = Metrics()


# -- env activation -----------------------------------------------------------

# The flight recorder is ON by default (the whole point is postmortems for
# faults nobody predicted); KEYSTONE_FLIGHT_DEPTH=0 turns it off.
set_flight_depth(_parse_flight_depth())

_env_path = os.environ.get(TRACE_ENV, "").strip()
if _env_path:
    try:
        enable(_env_path)
    except OSError as e:
        # A bad env var must not make the whole package unimportable for
        # tools that never asked to trace — but the user who DID ask gets
        # told on stderr (the logger tree has no handler this early).
        import sys as _sys

        _sys.stderr.write(
            f"keystone_tpu: {TRACE_ENV}={_env_path!r} is unusable ({e}) — "
            "tracing disabled\n"
        )
        _logger.error(
            "%s=%r unusable (%s) — tracing disabled", TRACE_ENV, _env_path, e
        )
