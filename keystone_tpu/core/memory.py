"""HBM admission control: preflight memory planning + graceful degradation.

VERDICT r5's top finding was that the flagship fused solvers discovered OOM
as a bare ``RESOURCE_EXHAUSTED`` at execution time — a 4 GB design matrix
failing on a 16 GB chip with nothing saying *whose* memory died.  KeystoneML
never had this failure mode because Spark's block manager admitted or
spilled every cached partition against a known executor budget; this module
is that admission-control discipline rebuilt for a single-controller JAX
stack:

* :func:`hbm_budget` — the byte budget a fit may plan against:
  ``KEYSTONE_HBM_BUDGET`` (testing / policy override) or the live device's
  ``memory_stats()`` free bytes; ``None`` when neither is known (CPU
  backends), in which case admission is skipped, never guessed.
* :class:`MemoryPlan` / :func:`plan_program` — AOT-lower a candidate
  program on ``jax.ShapeDtypeStruct``s (NO data is allocated to plan),
  read ``compiled.memory_analysis()`` (argument/temp/output/alias bytes),
  add the caller's accounting of persistent buffers the program's argument
  list does not see (``extra_bytes``), and return admit/deny with the full
  breakdown.  An OOM is thereby diagnosed *before* execution, with numbers.
* :func:`run_ladder` — the graceful-degradation driver: an ordered list of
  :class:`Tier`\\ s (e.g. fused one-program → stepwise per-block →
  host-staged streaming) is walked with per-tier preflight; a denied tier
  is skipped with its reason counted, an admitted tier that still dies with
  ``RESOURCE_EXHAUSTED`` at runtime steps down exactly one tier instead of
  failing the fit.  The last tier is the floor — it runs even if its own
  preflight is pessimistic, because there is nothing below it.
* :class:`FitReport` — the audit trail (per-tier plans, chosen tier,
  denials, OOM retries) estimators expose as ``last_fit_report`` and the
  bench emits verbatim, so the OOM boundary is measured, not guessed.
* **Mesh mode** — ``plan_program(mesh=...)`` models a GSPMD program
  per chip: ``NamedSharding``-annotated avals charge their SHARD's bytes
  (replicated operands charge whole, conservatively), admission runs
  against the MINIMUM per-chip free HBM across ``mesh.devices``
  (:func:`min_chip_budget`), and the compiled SPMD module's own per-device
  ``memory_analysis()`` rides along as ground truth (``plan.reported``).
  The solvers' mesh ladders use it to step full mesh → reduced-model mesh
  → the single-device ladder instead of dying on one tight chip.

Temp-size caveat: CPU backends report ``temp_size_in_bytes == 0``, which
would make a fused program look cheaper than its own stepwise decomposition.
Callers that know a program's true transient floor pass it as
``min_temp_bytes``; the plan uses ``max(reported, analytic)``.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import re
import time
from typing import Any, Callable, Sequence

import jax

from . import profiler
from . import trace
from .resilience import counters

_logger = logging.getLogger("keystone_tpu.memory")

#: env var: byte budget override ("2G", "512M", "1.5T", or plain bytes).
HBM_BUDGET_ENV = "KEYSTONE_HBM_BUDGET"

_SUFFIX = {"": 1, "K": 2**10, "M": 2**20, "G": 2**30, "T": 2**40}


def parse_bytes(spec: str | int | float) -> int:
    """``"16G"`` / ``"512M"`` / ``"1.5GB"`` / ``4096`` -> bytes."""
    if isinstance(spec, (int, float)):
        return int(spec)
    m = re.fullmatch(
        r"\s*([0-9]+(?:\.[0-9]+)?)\s*([KMGT]?)I?B?\s*", str(spec).upper()
    )
    if not m:
        raise ValueError(
            f"cannot parse byte size {spec!r} (expected e.g. '16G', '512M', "
            "'1.5GB', or a plain byte count)"
        )
    return int(float(m.group(1)) * _SUFFIX[m.group(2)])


def fmt_bytes(b: int | float) -> str:
    """Human-scaled byte count for log/reason strings ('3.25GB', '514KB')."""
    b = float(b)
    for unit in ("B", "KB", "MB", "GB"):
        if abs(b) < 1024 or unit == "GB":
            return f"{b:.2f}{unit}" if unit != "B" else f"{int(b)}B"
        b /= 1024
    return f"{b:.2f}TB"  # pragma: no cover


def budget_is_live() -> bool:
    """True when :func:`hbm_budget` reads LIVE free bytes (device
    ``memory_stats``) rather than the ``KEYSTONE_HBM_BUDGET`` capacity
    override.  The distinction matters for admission: a live free-bytes
    budget already excludes device-resident inputs, so their bytes must be
    credited back out of a plan's total (``plan_program(resident_bytes=)``)
    or a fit whose matrix is already on-chip double-counts it and degrades
    needlessly; a capacity-style env budget must charge them."""
    return not os.environ.get(HBM_BUDGET_ENV, "").strip()


def min_chip_budget(mesh) -> tuple[int | None, Any]:
    """``(budget_bytes, device)``: the SMALLEST per-chip byte budget across
    ``mesh.devices`` and the chip it came from — what a GSPMD program must
    be admitted against, because XLA allocates the sharded program on every
    participating chip and the tightest one is the one that OOMs.

    ``KEYSTONE_HBM_BUDGET`` keeps its override role with PER-CHIP capacity
    semantics (a mesh of 16 GB chips is ``16G``, not ``256G``).  Without the
    env, every device's live ``memory_stats()`` free bytes are read; if ANY
    participating chip cannot report (CPU backends), the answer is
    ``(None, None)`` — admission is skipped, never guessed from a subset of
    the mesh.  On a mesh spanning PROCESSES only the chips addressable
    from this host are consulted — a remote chip's ``memory_stats()``
    cannot be read here, and in a symmetric fleet the local minimum IS the
    per-chip answer; a mesh with no local chips at all answers
    ``(None, None)``."""
    raw = os.environ.get(HBM_BUDGET_ENV, "").strip()
    if raw:
        return parse_bytes(raw), None
    me = jax.process_index()
    local = [d for d in mesh.devices.flat if d.process_index == me]
    if not local:
        return None, None
    worst: int | None = None
    worst_dev = None
    for dev in local:
        free = hbm_budget(dev)
        if free is None:
            return None, None
        if worst is None or free < worst:
            worst, worst_dev = free, dev
    return worst, worst_dev


def shard_bytes(aval, mesh=None) -> int:
    """Per-chip bytes of one array/ShapeDtypeStruct under its sharding.

    A ``NamedSharding``-annotated aval contributes its SHARD's bytes (the
    sharding's per-device ``shard_shape``); anything un-annotated — or
    annotated replicated — contributes its full bytes, the conservative
    fallback (a replicated operand really does occupy full size on every
    chip).  This is the per-axis division the mesh admission model is built
    on: a ``(data=4, model=2)``-sharded design matrix charges 1/4 of its
    global bytes to each chip, its replicated gram factors charge whole."""
    import numpy as np

    n = 1
    for dim in aval.shape:
        n *= int(dim)
    total = n * np.dtype(aval.dtype).itemsize
    sharding = getattr(aval, "sharding", None)
    if sharding is None or not hasattr(sharding, "shard_shape"):
        return total
    try:
        shard = sharding.shard_shape(tuple(aval.shape))
    except Exception:  # noqa: BLE001 — unshardable spec: charge whole
        return total
    m = 1
    for dim in shard:
        m *= int(dim)
    return m * np.dtype(aval.dtype).itemsize


def hbm_budget(device=None) -> int | None:
    """Bytes a program may plan against, or ``None`` when unknowable.

    Priority: ``KEYSTONE_HBM_BUDGET`` env (tests force degradation tiers
    with it; capacity semantics — resident inputs charge against it) > the
    device's live ``memory_stats()`` free bytes (limit minus in-use — the
    same numbers Spark's block manager admitted against; already-resident
    inputs are credited via ``plan_program(resident_bytes=)``) > ``None``
    (CPU and other backends without stats: admission is skipped, the
    solver runs its first tier exactly as before this module existed).
    """
    raw = os.environ.get(HBM_BUDGET_ENV, "").strip()
    if raw:
        return parse_bytes(raw)
    device = device if device is not None else jax.devices()[0]
    try:
        stats = device.memory_stats()
    except Exception:  # noqa: BLE001 — backends without stats
        return None
    if not stats:
        return None
    limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
    if not limit:
        return None
    return int(limit) - int(stats.get("bytes_in_use", 0))


@dataclasses.dataclass
class MemoryPlan:
    """Admit/deny verdict for one candidate program, with the evidence."""

    label: str
    admitted: bool
    reason: str
    budget_bytes: int | None = None
    argument_bytes: int = 0
    temp_bytes: int = 0
    output_bytes: int = 0
    alias_bytes: int = 0
    extra_bytes: int = 0  # persistent buffers outside the program's args
    resident_bytes: int = 0  # of total, already allocated on device
    total_bytes: int = 0
    analyzed: bool = False  # False: no compile happened (no budget known)
    #: mesh mode: the (data, model) axis sizes the per-chip numbers assume.
    #: When set, argument/temp/output/total_bytes above are PER-CHIP.
    mesh_axes: dict | None = None
    #: mesh mode: the raw ``memory_analysis()`` numbers of the compiled
    #: SPMD module (XLA's own per-device accounting) kept alongside the
    #: analytic per-axis division, so the admission model is auditable
    #: against ground truth in every record.
    reported: dict | None = None
    error: str | None = None
    compiled: Any = dataclasses.field(default=None, repr=False, compare=False)

    def breakdown(self) -> dict:
        """JSON-able record for bench artifacts (GB, 3 decimals)."""
        gb = lambda b: round(b / 2**30, 3)  # noqa: E731
        out = {
            "admitted": self.admitted,
            "analyzed": self.analyzed,
            "argument_gb": gb(self.argument_bytes),
            "temp_gb": gb(self.temp_bytes),
            "output_gb": gb(self.output_bytes),
            "alias_gb": gb(self.alias_bytes),
            "extra_gb": gb(self.extra_bytes),
            "resident_gb": gb(self.resident_bytes),
            "total_gb": gb(self.total_bytes),
            "budget_gb": gb(self.budget_bytes) if self.budget_bytes else None,
            "reason": self.reason,
        }
        if self.mesh_axes is not None:
            out["per_chip"] = True
            out["mesh"] = dict(self.mesh_axes)
            if self.reported is not None:
                out["xla_reported_gb"] = {
                    k: gb(v) for k, v in self.reported.items()
                }
        if self.error:
            out["error"] = self.error[:200]
        return out


def _admission_event(plan: "MemoryPlan") -> "MemoryPlan":
    """Every admission decision is a point event on the trace timeline:
    charged bytes vs budget, per-chip mesh axes when in mesh mode — the
    trace shows WHY a tier was denied next to the tier spans that ran.
    The event args ARE ``plan.breakdown()`` (the same record bench emits),
    so the two can never drift apart."""
    trace.instant(
        "hbm_admission",
        **{
            "label": plan.label,
            "per_chip": plan.mesh_axes is not None,
            **plan.breakdown(),
        },
    )
    return plan


_UNSET = object()
# (fn, arg signature) -> dict of analysis numbers + compiled object;
# admission is re-evaluated against the CURRENT budget on every call, but the
# AOT lower+compile (the expensive part) happens once per program signature.
# Entries hold the compiled EXECUTABLE (so an admitted plan executes the very
# program that was planned) — callers probing many throwaway shapes (the
# at-scale bench) call clear_plan_cache() afterwards to release them.
_plan_cache: dict = {}


#: label -> number of REAL AOT lower+compiles plan_program performed (cache
#: misses only).  The AOT-reuse contract — "the per-block program compiles
#: exactly once: at preflight" — is asserted against this in the tests.
_compile_counts: dict[str, int] = {}


def compile_count(label: str) -> int:
    """How many times a plan labeled ``label`` actually compiled (plan-cache
    hits don't count — they reuse the executable)."""
    return _compile_counts.get(label, 0)


def clear_plan_cache() -> None:
    """Drop every cached plan analysis AND its compiled executable.  Loaded
    executables can reserve device program memory; probe-style callers
    (bench_solve_at_scale walks five multi-GB shapes) clear the cache once
    the boundary is measured so the reservations don't outlive the probe."""
    _plan_cache.clear()


def _cache_key(fn, args, kwargs):
    sig = []
    for a in (*args, *sorted(kwargs.items())):
        if hasattr(a, "shape") and hasattr(a, "dtype"):
            # The sharding is part of the compiled program's identity: the
            # same shapes planned for a (4, 2) mesh and an (8, 1) mesh are
            # different SPMD modules with different per-chip footprints.
            sharding = getattr(a, "sharding", None)
            sig.append(("arr", tuple(a.shape), str(a.dtype), str(sharding)))
        else:
            sig.append(("static", a))
    return (id(fn), tuple(sig))


def _per_chip_output_bytes(fn, args, kwargs, compiled) -> int | None:
    """Analytic per-chip output bytes of a planned SPMD program: the out
    avals (``eval_shape`` — abstract, allocates nothing) divided by the
    compiled executable's actual output shardings.  ``None`` when either
    side is unavailable (old jaxlib without ``output_shardings``, or a
    tree-shape mismatch) — the caller falls back to XLA's reported number."""
    try:
        out_avals = jax.tree_util.tree_leaves(jax.eval_shape(fn, *args, **kwargs))
        out_shardings = jax.tree_util.tree_leaves(compiled.output_shardings)
        if len(out_avals) != len(out_shardings):
            return None
        total = 0
        for aval, sh in zip(out_avals, out_shardings):
            total += shard_bytes(
                jax.ShapeDtypeStruct(aval.shape, aval.dtype, sharding=sh)
            )
        return total
    except Exception:  # noqa: BLE001 — advisory refinement only
        return None


def plan_program(
    fn,
    *args,
    label: str = "program",
    budget: int | None | object = _UNSET,
    extra_bytes: int = 0,
    min_temp_bytes: int = 0,
    resident_bytes: int = 0,
    require_analysis: bool = False,
    mesh=None,
    **kwargs,
) -> MemoryPlan:
    """Preflight ``fn`` (a ``jax.jit``-wrapped callable) on ``args``.

    ``args`` may be real arrays OR ``jax.ShapeDtypeStruct``s — planning
    allocates nothing.  When a budget is known (or ``require_analysis``),
    the program is AOT lowered+compiled (cached per signature; the returned
    plan carries ``compiled`` so an admitted fused program executes the very
    executable that was planned, not a recompile) and admission compares

        argument + max(temp, min_temp_bytes) + output − alias + extra

    against the budget.  ``resident_bytes`` declares how much of that total
    is ALREADY allocated on device (e.g. a device-resident design matrix
    among the arguments): a live free-bytes budget (:func:`budget_is_live`)
    excludes those bytes from free, so they are credited back before the
    comparison; a capacity-style ``KEYSTONE_HBM_BUDGET`` charges them.
    With no budget and no ``require_analysis`` the plan is a zero-cost
    pass-through: admitted, unanalyzed, reason recorded.  Denials are
    counted under ``hbm_preflight_denied``.

    **Mesh mode** (``mesh=`` a ``jax.sharding.Mesh``): the program is a
    GSPMD solve and every byte figure becomes PER-CHIP.  Arguments and
    outputs are divided by the per-axis sharding of each
    ``NamedSharding``-annotated aval (:func:`shard_bytes`; replicated or
    un-annotated operands conservatively charge full size — they really do
    live whole on every chip), and the default budget is the MINIMUM
    per-chip free HBM across ``mesh.devices`` (:func:`min_chip_budget`;
    ``KEYSTONE_HBM_BUDGET`` overrides with per-chip capacity semantics).
    The compiled SPMD module's own ``memory_analysis()`` — which XLA also
    reports per device — is kept in ``plan.reported`` as the ground truth
    the analytic division is audited against; admission charges the LARGER
    of the two for each category, so a spec the analytic model cannot see
    through (e.g. a resharded intermediate) still cannot under-admit.
    ``resident_bytes`` credit is not modeled per chip; mesh callers pass 0.
    """
    if mesh is not None and budget is _UNSET:
        budget, _worst = min_chip_budget(mesh)
    if budget is _UNSET:
        budget = hbm_budget()
    # With the profiler ON the zero-cost skip still compiles (ISSUE 14):
    # the cost-attribution ledger and the flops audit need the compiled
    # executable's cost_analysis, and the compile is work the admitted
    # tier was about to do anyway (plan.compiled is what executes).
    # Admission itself stays skipped — budget None never denies.
    if budget is None and not require_analysis and not profiler.enabled():
        return _admission_event(MemoryPlan(
            label=label,
            admitted=True,
            reason=(
                "no HBM budget known (no device memory_stats and "
                f"{HBM_BUDGET_ENV} unset) — admission skipped"
            ),
            mesh_axes=dict(mesh.shape) if mesh is not None else None,
        ))

    key = _cache_key(fn, args, kwargs)
    cached = _plan_cache.get(key)
    if cached is None:
        try:
            compiled = fn.lower(*args, **kwargs).compile()
            ma = compiled.memory_analysis()
            cached = {
                "argument": int(ma.argument_size_in_bytes),
                "temp": int(ma.temp_size_in_bytes),
                "output": int(ma.output_size_in_bytes),
                "alias": int(ma.alias_size_in_bytes),
                "compiled": compiled,
                "error": None,
            }
            if mesh is not None:
                cached["sharded_out"] = _per_chip_output_bytes(
                    fn, args, kwargs, compiled
                )
            # Only SUCCESSFUL analyses are cached: a compile failure can be
            # transient (program-memory pressure from live buffers), and
            # caching it would deny this tier for the rest of the process.
            _plan_cache[key] = cached
            _compile_counts[label] = _compile_counts.get(label, 0) + 1
        except Exception as e:  # noqa: BLE001 — a compile OOM IS an answer
            cached = {"error": f"{type(e).__name__}: {e}"[:300]}

    if cached["error"] is not None:
        if budget is None and not require_analysis:
            # The compile only happened because the PROFILER asked for
            # attribution (the budget-less skip above) — attribution is
            # advisory, so its failure must admit exactly like the
            # unprofiled skip would: enabling the profiler can never
            # deny a tier an unprofiled run would have executed.
            return _admission_event(MemoryPlan(
                label=label,
                admitted=True,
                reason=(
                    "no HBM budget known — admission skipped (profiler "
                    f"attribution compile failed: {cached['error'][:120]})"
                ),
                mesh_axes=dict(mesh.shape) if mesh is not None else None,
                error=cached["error"],
            ))
        plan = MemoryPlan(
            label=label,
            admitted=False,
            reason=f"lower/compile failed: {cached['error'][:120]}",
            budget_bytes=budget,
            analyzed=False,
            mesh_axes=dict(mesh.shape) if mesh is not None else None,
            error=cached["error"],
        )
        counters.record("hbm_preflight_denied", f"{label}: {plan.reason}")
        return _admission_event(plan)

    reported = None
    if mesh is None:
        arg_bytes = cached["argument"]
        out_bytes = cached["output"]
    else:
        reported = {
            k: cached[k] for k in ("argument", "temp", "output", "alias")
        }
        # Analytic per-axis division of the argument avals; XLA's own
        # per-device module accounting is the floor (max of the two), so a
        # replicated-in-practice operand the annotations promised sharded
        # still charges what the compiled module will really hold.
        analytic_args = sum(
            shard_bytes(a)
            for a in (*args, *(v for _, v in sorted(kwargs.items())))
            if hasattr(a, "shape") and hasattr(a, "dtype")
        )
        arg_bytes = max(analytic_args, cached["argument"])
        sharded_out = cached.get("sharded_out")
        out_bytes = (
            max(sharded_out, cached["output"])
            if sharded_out is not None
            else cached["output"]
        )

    temp = max(cached["temp"], min_temp_bytes)
    total = arg_bytes + temp + out_bytes - cached["alias"] + extra_bytes
    credit = resident_bytes if budget_is_live() else 0
    admitted = budget is None or total - credit <= budget
    h = fmt_bytes
    reason = (
        ("per-chip " if mesh is not None else "")
        + f"args {h(arg_bytes)} + temp {h(temp)} + "
        f"out {h(out_bytes)} - alias {h(cached['alias'])} "
        f"+ extra {h(extra_bytes)} = {h(total)}"
        + (f" (- {h(credit)} already resident)" if credit else "")
        + " vs "
        + (
            f"min-free-chip budget {h(budget)} on mesh {dict(mesh.shape)}"
            if mesh is not None and budget is not None
            else f"budget {h(budget)}" if budget is not None else "no budget"
        )
    )
    plan = MemoryPlan(
        label=label,
        admitted=admitted,
        reason=("fits: " if admitted else "DENIED: ") + reason,
        budget_bytes=budget,
        argument_bytes=arg_bytes,
        temp_bytes=temp,
        output_bytes=out_bytes,
        alias_bytes=cached["alias"],
        extra_bytes=extra_bytes,
        resident_bytes=resident_bytes,
        total_bytes=total,
        analyzed=True,
        mesh_axes=dict(mesh.shape) if mesh is not None else None,
        reported=reported,
        compiled=cached["compiled"],
    )
    if not admitted:
        counters.record("hbm_preflight_denied", f"{label}: {reason}")
    return _admission_event(plan)


def plan_bytes(
    label: str,
    *,
    argument_bytes: int = 0,
    temp_bytes: int = 0,
    output_bytes: int = 0,
    extra_bytes: int = 0,
    resident_bytes: int = 0,
    mesh=None,
    budget: int | None | object = _UNSET,
) -> MemoryPlan:
    """ANALYTIC-ONLY admission of a candidate program from caller-supplied
    per-chip byte figures — no lower, no compile, no cache entry: the
    zero-cost half of the placement search's candidate-batch preflight
    (core.autoshard prunes enumerated candidates with this before any of
    them is worth an AOT compile).

    Deliberately a LOWER BOUND on what :func:`plan_program` would charge
    (no alias credit is modeled, and callers pass only the transient floors
    they can prove): a plan denied here is denied a fortiori by the
    compiled preflight, while an admitted one still faces the full
    admission when the ladder actually selects it — pruning can skip work,
    never under-admit.  Same budget/credit semantics as ``plan_program``
    (min per-chip free HBM under a ``mesh``; resident credit only against a
    live free-bytes budget); denials are counted under
    ``hbm_preflight_denied`` like any other admission decision."""
    if mesh is not None and budget is _UNSET:
        budget, _worst = min_chip_budget(mesh)
    if budget is _UNSET:
        budget = hbm_budget()
    mesh_axes = dict(mesh.shape) if mesh is not None else None
    if budget is None:
        return _admission_event(MemoryPlan(
            label=label,
            admitted=True,
            reason=(
                "no HBM budget known (no device memory_stats and "
                f"{HBM_BUDGET_ENV} unset) — analytic admission skipped"
            ),
            argument_bytes=int(argument_bytes),
            temp_bytes=int(temp_bytes),
            output_bytes=int(output_bytes),
            extra_bytes=int(extra_bytes),
            resident_bytes=int(resident_bytes),
            total_bytes=int(
                argument_bytes + temp_bytes + output_bytes + extra_bytes
            ),
            mesh_axes=mesh_axes,
        ))
    total = int(argument_bytes + temp_bytes + output_bytes + extra_bytes)
    credit = int(resident_bytes) if budget_is_live() else 0
    admitted = total - credit <= budget
    h = fmt_bytes
    reason = (
        ("fits: " if admitted else "DENIED: ")
        + ("per-chip " if mesh is not None else "")
        + f"analytic args {h(argument_bytes)} + temp {h(temp_bytes)} + "
        f"out {h(output_bytes)} + extra {h(extra_bytes)} = {h(total)}"
        + (f" (- {h(credit)} already resident)" if credit else "")
        + f" vs budget {h(budget)} (no compile)"
    )
    plan = MemoryPlan(
        label=label,
        admitted=admitted,
        reason=reason,
        budget_bytes=budget,
        argument_bytes=int(argument_bytes),
        temp_bytes=int(temp_bytes),
        output_bytes=int(output_bytes),
        extra_bytes=int(extra_bytes),
        resident_bytes=int(resident_bytes),
        total_bytes=total,
        analyzed=False,  # no compile happened — analytic numbers only
        mesh_axes=mesh_axes,
    )
    if not admitted:
        counters.record("hbm_preflight_denied", f"{label}: {reason}")
    return _admission_event(plan)


def plan_batch(
    planners: Sequence[tuple[str, Callable[[], MemoryPlan]]],
) -> dict[str, MemoryPlan]:
    """Candidate-batch preflight: evaluate every ``(label, planner)`` pair
    and return ``{label: MemoryPlan}``.  A planner that RAISES becomes a
    denied plan carrying the error (one broken candidate must not kill the
    search over the others) — the batch analog of ``plan_program``'s
    compile-failure-is-an-answer rule."""
    out: dict[str, MemoryPlan] = {}
    for label, planner in planners:
        try:
            out[label] = planner()
        except Exception as e:  # noqa: BLE001 — a failed plan IS a deny
            out[label] = _admission_event(MemoryPlan(
                label=label,
                admitted=False,
                reason=f"planner failed: {type(e).__name__}: {e}"[:200],
                error=f"{type(e).__name__}: {e}"[:300],
            ))
    return out


def plan_cache_bytes(
    label: str,
    nbytes: int,
    *,
    mesh=None,
    budget: int | None | object = _UNSET,
    headroom: float = 0.5,
) -> MemoryPlan:
    """Admit or deny holding ``nbytes`` of materialized intermediates
    resident — the auto-Cacher's admission gate (core.optimize).  Data-only:
    no program to compile, so admission is a straight byte comparison
    against the HBM budget (the minimum per-chip free HBM under a ``mesh``,
    exactly like :func:`plan_program`'s mesh mode; callers divide sharded
    cache bytes per chip before calling).

    ``headroom``: fraction of the budget caches may claim — a cache that
    fills ALL free HBM starves the very solve it was meant to speed up, so
    the default admits at most half.  No budget known -> admitted
    unanalyzed (CPU backends without stats), same skip-never-guess rule as
    every other admission path.  Denials are counted under
    ``cache_admission_denied`` and land on the trace timeline as
    ``hbm_admission`` events like any program plan."""
    if mesh is not None and budget is _UNSET:
        budget, _worst = min_chip_budget(mesh)
    if budget is _UNSET:
        budget = hbm_budget()
    mesh_axes = dict(mesh.shape) if mesh is not None else None
    if budget is None:
        return _admission_event(MemoryPlan(
            label=label,
            admitted=True,
            reason=(
                "no HBM budget known (no device memory_stats and "
                f"{HBM_BUDGET_ENV} unset) — cache admission skipped"
            ),
            output_bytes=int(nbytes),
            total_bytes=int(nbytes),
            mesh_axes=mesh_axes,
        ))
    allowed = int(budget * headroom)
    admitted = int(nbytes) <= allowed
    h = fmt_bytes
    reason = (
        ("fits: " if admitted else "DENIED: ")
        + ("per-chip " if mesh is not None else "")
        + f"cached {h(nbytes)} vs {h(allowed)} "
        f"(budget {h(budget)} x headroom {headroom})"
    )
    plan = MemoryPlan(
        label=label,
        admitted=admitted,
        reason=reason,
        budget_bytes=allowed,
        output_bytes=int(nbytes),
        total_bytes=int(nbytes),
        analyzed=True,
        mesh_axes=mesh_axes,
    )
    if not admitted:
        counters.record("cache_admission_denied", f"{label}: {reason}")
    return _admission_event(plan)


# -- OOM detection / recovery -------------------------------------------------

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory")


class LadderSourceLost(RuntimeError):
    """A ladder tier cannot run because its data source was donated away
    (``fit(donate=True)`` consumed the caller's buffers and a later tier
    has nothing to rebuild from).  Deliberately NOT an OOM: the ladder must
    surface it, not retry through it."""


def is_oom_error(e: BaseException) -> bool:
    """True for XLA's device-memory exhaustion (``XlaRuntimeError`` carrying
    RESOURCE_EXHAUSTED / out-of-memory text) — the ONLY failure the
    degradation ladder retries; everything else — including the ladder's
    own :class:`LadderSourceLost` guard — propagates unchanged."""
    if isinstance(e, LadderSourceLost):
        return False
    if not isinstance(e, (RuntimeError, MemoryError)):
        return False
    msg = str(e)
    return isinstance(e, MemoryError) or any(m in msg for m in _OOM_MARKERS)


def free_buffers(*arrays) -> None:
    """Best-effort immediate release of device buffers (OOM recovery frees
    the failed tier's live arrays before retrying a cheaper tier, rather
    than waiting on the GC)."""
    for a in arrays:
        if isinstance(a, jax.Array):
            try:
                if not a.is_deleted():
                    a.delete()
            except Exception:  # noqa: BLE001 — freeing is advisory
                pass


def array_bytes(*shaped) -> int:
    """Σ nbytes of arrays/ShapeDtypeStructs (resident-set accounting for
    ``plan_program(extra_bytes=...)``)."""
    import numpy as np

    total = 0
    for s in shaped:
        if s is None:
            continue
        n = 1
        for dim in s.shape:
            n *= int(dim)
        total += n * np.dtype(s.dtype).itemsize
    return total


# -- the degradation ladder ---------------------------------------------------


@dataclasses.dataclass
class Tier:
    """One rung: ``plan`` is lazy (called at selection time), ``run`` gets
    the plan back so an admitted fused tier can execute ``plan.compiled``."""

    name: str
    plan: Callable[[], MemoryPlan]
    run: Callable[[MemoryPlan], Any]


@dataclasses.dataclass
class FitReport:
    """Audit trail of one laddered fit (``estimator.last_fit_report``)."""

    label: str = ""
    budget_bytes: int | None = None
    plans: dict = dataclasses.field(default_factory=dict)
    chosen: str | None = None
    denials: list = dataclasses.field(default_factory=list)
    oom_retries: list = dataclasses.field(default_factory=list)
    #: the placement search's program fingerprint (set by
    #: autoshard.run_search) — the grouping key the profiler's HBM
    #: watermark drift rows use, so byte-drift evidence joins the same
    #: program family as the time outcomes.
    fingerprint: str | None = None
    #: mesh ladders: the (data, model) axis sizes of the mesh that actually
    #: RAN the solve; ``None`` after a step-down to the single-device floor
    #: (and for plain single-device fits).
    mesh_shape: dict | None = None
    #: placement search (core.autoshard): the PlacementPlan record of the
    #: searched ranking this fit ran through — the full candidate table
    #: with deny/score rationale and the chosen plan's predicted-vs-actual
    #: cost.  ``None`` when the fit walked the hand ladder.
    placement: dict | None = None
    #: numerics observatory (core.numerics, KEYSTONE_NUMERICS=1): per-block
    #: κ estimates of this solve's gram blocks — the ACCURACY.md §6 offline
    #: sweep as a live per-fit monitor.  ``None`` when the observatory was
    #: off for the fit.
    conditioning: list | None = None

    def record(self) -> dict:
        """JSON-able form for bench artifacts."""
        from . import telemetry

        return {
            "chosen_tier": self.chosen,
            "conditioning": (
                list(self.conditioning) if self.conditioning else None
            ),
            "mesh_shape": dict(self.mesh_shape) if self.mesh_shape else None,
            "budget_gb": (
                round(self.budget_bytes / 2**30, 3) if self.budget_bytes else None
            ),
            "denials": list(self.denials),
            "oom_retries": list(self.oom_retries),
            "tiers": {k: p.breakdown() for k, p in self.plans.items()},
            "placement": self.placement,
            # Flight-recorder postmortems this process has dumped
            # (core.telemetry) — a degraded fit links to its evidence.
            "postmortems": telemetry.postmortem_paths(),
        }

    def summary(self) -> str:
        s = f"{self.label}: tier={self.chosen}"
        if self.mesh_shape:
            s += f", mesh={self.mesh_shape}"
        if self.denials:
            s += f", denied={self.denials}"
        if self.oom_retries:
            s += f", oom_retries={self.oom_retries}"
        return s

    def degraded(self) -> bool:
        return bool(self.denials or self.oom_retries)


def run_ladder(label: str, tiers: Sequence[Tier], report: FitReport):
    """Walk ``tiers`` best-first: preflight each LAZILY (a tier is only
    planned — and its program only compiled — once every better tier has
    been denied or OOMed, so the common fused-admitted fit pays for exactly
    one plan), run the first admitted one, and on a runtime
    ``RESOURCE_EXHAUSTED`` step down exactly one tier (the tier's ``run``
    frees its own buffers on the way out; anything it leaked is
    best-effort-freed by the next tier's builder).  The final tier is the
    floor: it runs even when its preflight is a deny — with a warning —
    because failing is the only thing below it.  Every CONSIDERED tier's
    plan lands in ``report`` so the decision is auditable afterwards.
    """
    report.label = label
    last_oom: BaseException | None = None
    # The whole laddered solve is one span; each considered tier's plan and
    # run are child spans, and the FitReport is linked into the solve span
    # at exit — a trace shows which tiers were tried, denied, OOMed, and
    # chosen, with the admission numbers alongside.
    with trace.span(f"solve:{label}", cat="solve") as solve_sp:
        for i, tier in enumerate(tiers):
            floor = i == len(tiers) - 1
            with trace.span(f"plan:{tier.name}", cat="solve", solve=label):
                plan = tier.plan()
            report.plans[tier.name] = plan
            if plan.budget_bytes is not None:
                report.budget_bytes = plan.budget_bytes
            if not plan.admitted and not floor:
                report.denials.append(tier.name)
                _logger.info("%s: %s denied by preflight — %s", label, tier.name, plan.reason)
                continue
            if not plan.admitted and floor:
                _logger.warning(
                    "%s: floor tier %s denied by preflight (%s) but nothing is "
                    "below it — attempting anyway",
                    label, tier.name, plan.reason,
                )
            try:
                with trace.span(
                    f"tier:{tier.name}", cat="solve",
                    solve=label, admitted=plan.admitted,
                ), profiler.phase(f"solve:{label}"):
                    t_run = time.perf_counter()
                    out = tier.run(plan)
            except Exception as e:  # noqa: BLE001 — only OOM is retried
                if not is_oom_error(e) or floor:
                    raise
                report.oom_retries.append(tier.name)
                counters.record(
                    "solver_oom_retry",
                    f"{label}/{tier.name}: RESOURCE_EXHAUSTED at runtime "
                    f"(preflight said: {plan.reason}) — stepping down one tier",
                )
                last_oom = e
                continue
            report.chosen = tier.name
            if report.degraded() or tier.name != tiers[0].name:
                counters.record("solver_tier_degraded", report.summary())
            _logger.info("%s: running tier=%s (%s)", label, tier.name, plan.reason)
            if profiler.enabled():
                # Device cost attribution (ISSUE 14): the chosen tier's
                # compiled program lands in the per-program MFU ledger
                # with its device-synced wall, and the HBM watermark the
                # sampler saw during the solve is audited against what
                # this plan CHARGED — drift is counted and logged as
                # calibration evidence.  One enabled() check when off.
                wall = profiler.synced_wall(out, t_run)
                if plan.compiled is not None:
                    profiler.record_program(
                        f"{label}:{tier.name}", plan.compiled, wall
                    )
                profiler.audit_plan(
                    f"{label}:{tier.name}", plan,
                    phase_name=f"solve:{label}",
                    fingerprint=report.fingerprint,
                )
            solve_sp.set(report=report.record())
            return out
        # Unreachable in practice (the floor either returns or raises), but
        # be explicit if a caller builds a ladder whose floor denied AND
        # raised.
        raise RuntimeError(
            f"{label}: every ladder tier failed"
        ) from last_oom


def log_fit_report(est, logger=None, label: str = "") -> None:
    """Workload fit-path hook: surface which tier a solve actually ran on
    (one INFO line; degradations are already counted by the ladder)."""
    rep = getattr(est, "last_fit_report", None)
    if rep is None:
        return
    lg = logger or _logger
    lg.info("%s%s", f"{label}: " if label else "", rep.summary())
