"""Shape-routed serving front-end: one warm engine per request shape,
closed-loop engine add/retire, and cross-engine HBM admission.

``core.serve`` serves ONE request shape through one ``ServingEngine`` +
``Server`` pair — the static-shape discipline XLA wants.  A production
endpoint sees a *mix* of request shapes (several image geometries, several
feature widths), and the mix drifts.  This module is the front-end tier
that turns the single-shape engines into one multi-shape service:

* **ShapeRouter** — holds one ``(ServingEngine, Server)`` pair per request
  shape (each engine is a whole batch-bucket family: per-bucket AOT
  executables, dynamic batcher, its own SLO tracker) and routes every
  request to the engine whose example shape it matches.  Each engine's
  label is per-shape (``<label>:<d0>x<d1>``), so ``KEYSTONE_SERVE_SLO_MS``'s
  ``label=ms`` syntax sets PER-SHAPE SLO targets and the telemetry
  registry's adopted ``slo`` group carries one tracker per live shape.
* **Warm add / retire from the observed mix** — the dynamic-batching
  analogue of the ingest autotuner's closed loop: requests for an unserved
  shape are counted in a rolling window and answered with a typed
  :class:`RetryLater` (explicit backpressure, never unbounded queueing);
  when a shape goes HOT (``warm_threshold`` requests inside
  ``mix_window_s``) the router warms a new engine from its
  ``engine_factory`` and serves the triggering request through it.  An
  engine that stops earning traffic (``retire_after_s`` idle) is retired:
  unrouted first, then DRAINED (every outstanding future resolves), then
  closed — an engine swap never drops a request.
* **Cross-engine admission** — every bucket of every engine is already
  admission-checked against the HBM budget by ``core.memory.plan_program``
  at compile time, but each engine plans in isolation; the router adds the
  missing cross-engine sum: a warm add is denied (counted
  ``router_admission_denied``, answered :class:`RetryLater`) when the new
  engine's peak-bucket bytes plus every live engine's would overrun the
  shared budget.  Denial is backpressure, not death — a later retire frees
  the headroom and the retry succeeds.  On a mesh-anchored router the
  budget is the anchor mesh's ``min_chip_budget`` — after a re-anchor the
  sum re-runs against the SURVIVING mesh's smallest chip, never the dead
  topology's.
* **Surviving-mesh re-anchor** (ISSUE 16) — :class:`MeshEngineFactory`
  walks the solvers' degradation ladder (full mesh → ``reduced_mesh`` →
  single device) when a tier's build fails, and
  :meth:`ShapeRouter.reanchor` hot-swaps every live engine onto a new
  (typically smaller, surviving) mesh through the same warm-add/
  drained-retire loop a mix shift uses: each replacement is built and
  registered BEFORE its predecessor is unrouted, the predecessor then
  drains (every outstanding future resolves) and closes — zero request
  loss across the reshard, counted ``mesh_reanchor`` (postmortem-linked).

Router state exports into ``trace.metrics`` (``router_engines`` gauge,
``router_routes``/``router_misses``/``router_warm_adds``/
``router_engine_retired`` counters, ``router_route_overhead_us``
histogram — the routing decision's own cost, the number the serving bench
regresses on), and every add/retire/denial lands on the trace timeline as
an instant event.

Env knobs (README ``KEYSTONE_*`` table):

* ``KEYSTONE_ROUTER_WARM_THRESHOLD`` — unserved-shape requests inside the
  mix window that trigger a warm engine add (default ``3``).
* ``KEYSTONE_ROUTER_MIX_WINDOW_S`` — rolling request-shape-mix window
  seconds (default ``5``).
* ``KEYSTONE_ROUTER_RETIRE_AFTER_S`` — idle seconds before an engine is
  retired (default ``30``).
* ``KEYSTONE_ROUTER_MAX_ENGINES`` — engine-count ceiling; at the ceiling a
  hot new shape can only warm by retiring the idlest engine (default ``8``).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from collections import deque
from typing import Callable

import numpy as np

from . import memory as kmem
from . import numerics as knum
from . import telemetry
from . import trace
from ..parallel import mesh as kmesh
from .resilience import counters
from .serve import (
    ServeConfig,
    ServeError,
    ServeFuture,
    Server,
    ServingEngine,
    ServingUnavailable,
)

_logger = logging.getLogger("keystone_tpu.frontend")

WARM_THRESHOLD_ENV = "KEYSTONE_ROUTER_WARM_THRESHOLD"
MIX_WINDOW_ENV = "KEYSTONE_ROUTER_MIX_WINDOW_S"
RETIRE_AFTER_ENV = "KEYSTONE_ROUTER_RETIRE_AFTER_S"
MAX_ENGINES_ENV = "KEYSTONE_ROUTER_MAX_ENGINES"


class NoRouteForShape(ServeError):
    """No live engine serves the request's shape and the router has no
    engine factory to warm one — a permanently unroutable request (the
    client should not retry the same shape)."""


class RetryLater(ServeError):
    """Typed backpressure: the request was NOT accepted (unserved shape
    still below the warm threshold, an engine mid-warm, or admission out
    of headroom) and the client should retry after ``retry_after_s``.
    The wire tier maps this 1:1 onto a RETRY_AFTER frame — explicit
    push-back instead of unbounded queueing."""

    def __init__(self, message: str, retry_after_s: float = 0.05):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


def _env_pos_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        val = int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not an integer") from None
    if val < 1:
        raise ValueError(f"{name}={raw!r} must be >= 1")
    return val


def _env_pos_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        val = float(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not a number") from None
    if val <= 0:
        raise ValueError(f"{name}={raw!r} must be > 0")
    return val


def shape_label(label: str, shape) -> str:
    """Per-shape engine label: ``<label>:<d0>x<d1>x...`` (``scalar`` for a
    rank-0 example) — the key ``KEYSTONE_SERVE_SLO_MS``'s per-label SLO
    override syntax targets."""
    dims = "x".join(str(int(d)) for d in shape)
    return f"{label}:{dims or 'scalar'}"


@dataclasses.dataclass
class RouterConfig:
    """Knob set of one shape router (env-seeded via :meth:`from_env`)."""

    #: unserved-shape requests inside the mix window that make the shape
    #: HOT (worth the compile cost of a warm engine add).
    warm_threshold: int = 3
    #: rolling window over which the request-shape mix is observed.
    mix_window_s: float = 5.0
    #: an engine idle this long stops earning its HBM and is retired.
    retire_after_s: float = 30.0
    #: never retire below this many engines.
    min_engines: int = 1
    #: engine-count ceiling; a hot shape at the ceiling can only warm by
    #: retiring the idlest engine.
    max_engines: int = 8
    #: the retry hint carried by :class:`RetryLater` rejections.
    retry_after_s: float = 0.05
    #: opportunistic adapt cadence on the submit path (a background thread
    #: runs the retire sweep; the hot path only reads a clock).
    adapt_interval_s: float = 2.0
    #: graceful-retire drain budget: outstanding futures get this long to
    #: resolve before the server is closed anyway (typed, never hung).
    drain_timeout_s: float = 30.0

    def __post_init__(self):
        if self.warm_threshold < 1:
            raise ValueError(
                f"warm_threshold must be >= 1, got {self.warm_threshold}"
            )
        if self.mix_window_s <= 0 or self.retire_after_s < 0:
            raise ValueError(
                "mix_window_s must be > 0 and retire_after_s >= 0"
            )
        if self.min_engines < 0 or self.max_engines < 1:
            raise ValueError(
                "min_engines must be >= 0 and max_engines >= 1"
            )

    @classmethod
    def from_env(cls, **overrides) -> "RouterConfig":
        cfg = {
            "warm_threshold": _env_pos_int(WARM_THRESHOLD_ENV, 3),
            "mix_window_s": _env_pos_float(MIX_WINDOW_ENV, 5.0),
            "retire_after_s": _env_pos_float(RETIRE_AFTER_ENV, 30.0),
            "max_engines": _env_pos_int(MAX_ENGINES_ENV, 8),
        }
        cfg.update({k: v for k, v in overrides.items() if v is not None})
        return cls(**cfg)

    def record(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class RouterStats:
    """Counters of one router's lifetime (bench/chaos artifact)."""

    routes: int = 0  #: requests routed to a live engine
    misses: int = 0  #: requests whose shape had no live engine
    warm_adds: int = 0  #: engines warmed from the observed mix
    retires: int = 0  #: engines retired (drained, closed, unregistered)
    rejected: int = 0  #: RetryLater answers (backpressure, retryable)
    replaces: int = 0  #: atomic per-shape engine swaps (lifecycle refits)
    admission_denied: int = 0  #: warm adds denied by the shared HBM budget
    no_route: int = 0  #: NoRouteForShape answers (no factory — permanent)

    def record(self) -> dict:
        return dataclasses.asdict(self)


class _Entry:
    """One live shape family: engine + its batcher, plus mix accounting."""

    __slots__ = ("key", "engine", "server", "added_at", "last_routed", "routes")

    def __init__(self, key: tuple, engine: ServingEngine, server: Server, now: float):
        self.key = key
        self.engine = engine
        self.server = server
        self.added_at = now
        self.last_routed = now
        self.routes = 0


class MeshEngineFactory:
    """Mesh-aware engine factory (ISSUE 16): builds engines anchored on a
    target mesh, walking the solvers' ``_fit_mesh_ladder`` degradation
    tiers — anchor mesh → ``reduced_mesh`` (same devices, model axis
    collapsed) → single-device floor — when a tier's build raises a typed
    :class:`~.serve.ServeError` (per-chip admission denial, no surviving
    bucket).  Each step down is counted ``router_mesh_stepdown``; only
    when the single-device floor also fails does the factory raise.

    ``build(shape, dtype, mesh_or_none) -> ServingEngine`` constructs one
    engine on one tier (``None`` = meshless single-device engine).  The
    anchor moves with the substrate: :meth:`ShapeRouter.reanchor` calls
    :meth:`set_mesh` with the surviving mesh, and every later build walks
    the NEW ladder.
    """

    def __init__(self, build, mesh=None):
        self._build = build
        self._mesh_lock = threading.Lock()
        self._mesh = mesh

    @property
    def mesh(self):
        with self._mesh_lock:
            return self._mesh

    def set_mesh(self, mesh) -> None:
        """Move the anchor (the surviving mesh after device loss)."""
        with self._mesh_lock:
            self._mesh = mesh

    def _ladder(self) -> list:
        mesh = self.mesh
        tiers = []
        if mesh is not None:
            tiers.append(mesh)
            reduced = kmesh.reduced_mesh(mesh)
            if reduced is not None:
                tiers.append(reduced)
        tiers.append(None)  # single-device floor: a meshless engine
        return tiers

    @staticmethod
    def _tier_desc(tier) -> str:
        return kmesh.mesh_desc(tier) if tier is not None else "single-device"

    @staticmethod
    def _denied_bucket(engine: ServingEngine) -> int | None:
        """A live bucket that only survived as the engine's denied floor
        (``ServingEngine`` keeps the floor bucket when preflight denies it
        rather than dying) — on a mesh tier that is per-chip admission
        failure, and a lower tier should be tried instead."""
        live = set(engine.buckets())
        for bucket, plan in engine.memory_plans.items():
            if bucket in live and not plan.admitted:
                return bucket
        return None

    def __call__(self, shape, dtype) -> ServingEngine:
        key = tuple(int(d) for d in shape)
        tiers = self._ladder()
        last_err: ServeError | None = None
        for i, tier in enumerate(tiers):
            try:
                engine = self._build(key, np.dtype(dtype), tier)
                denied = (
                    self._denied_bucket(engine) if tier is not None else None
                )
                if denied is None or i + 1 >= len(tiers):
                    return engine
                counters.record(
                    "router_mesh_stepdown",
                    f"engine for shape {key} on mesh "
                    f"{self._tier_desc(tier)} only serves through its "
                    f"DENIED floor bucket {denied} (per-chip admission) — "
                    f"stepping down to {self._tier_desc(tiers[i + 1])}",
                )
            except ServeError as e:
                last_err = e
                if i + 1 < len(tiers):
                    counters.record(
                        "router_mesh_stepdown",
                        f"engine for shape {key} failed to build on mesh "
                        f"{self._tier_desc(tier)} ({e}) — stepping down to "
                        f"{self._tier_desc(tiers[i + 1])}",
                    )
        raise ServingUnavailable(
            f"engine for shape {key} failed on every mesh tier "
            f"({', '.join(self._tier_desc(t) for t in tiers)}): {last_err}"
        ) from last_err


class ShapeRouter:
    """The multi-shape serving front-end: submit any supported-shape
    request, get a :class:`~.serve.ServeFuture` from the matching engine's
    batcher.

    ``engine_factory(shape, dtype) -> ServingEngine`` (optional) warms
    engines for hot unserved shapes; without it, unserved shapes answer
    :class:`NoRouteForShape`.  Engines added up front via
    :meth:`add_engine` serve immediately.  Use as a context manager (or
    call :meth:`close`).
    """

    def __init__(
        self,
        engine_factory: Callable[[tuple, np.dtype], ServingEngine] | None = None,
        *,
        label: str = "router",
        config: RouterConfig | None = None,
        server_config: ServeConfig | None = None,
        clock=time.monotonic,
        mesh=None,
    ):
        self._factory = engine_factory
        # The router's anchor mesh: cross-engine admission budgets against
        # ITS smallest chip (not the global hbm_budget), and reanchor()
        # moves it.  A MeshEngineFactory and the router share one anchor.
        if isinstance(engine_factory, MeshEngineFactory):
            if mesh is not None:
                engine_factory.set_mesh(mesh)
            else:
                mesh = engine_factory.mesh
        self._mesh = mesh
        self._last_reanchor: dict | None = None
        self.label = label
        self.config = config or RouterConfig.from_env()
        self._server_config = server_config
        self._clock = clock
        self._lock = threading.Lock()
        self._engines: dict[tuple, _Entry] = {}
        self._misses: dict[tuple, deque] = {}
        self._warming: set = set()
        #: shape -> peak bytes of an admitted-but-not-yet-registered warm
        #: add: concurrent warms for DIFFERENT shapes must see each
        #: other's claim, or two individually-fitting engines could
        #: jointly overrun the shared budget.
        self._warm_reserved: dict[tuple, int] = {}
        self.stats = RouterStats()
        #: JSON-able ledger of cross-engine admission verdicts (bench
        #: artifact — WHY a warm add was allowed/denied, with the bytes).
        self.admissions: list[dict] = []
        self._closed = False
        self._adapting = False
        self._last_adapt = self._clock()
        # The router's live state is a /statusz section (ISSUE 15): one
        # GET on the metrics port shows the engine table, per-engine drift
        # verdicts, and the admission ledger.  Unregistered at close(),
        # identity-guarded: a newer same-label router replaces this entry,
        # and this router's close must then NOT evict the newer one.
        self._statusz_provider = self.record
        telemetry.register_statusz(f"router:{label}", self._statusz_provider)

    # -- engine lifecycle -----------------------------------------------------

    def add_engine(self, engine: ServingEngine) -> tuple:
        """Register a pre-built engine (and its batcher) for its example
        shape.  Returns the routing key (the shape tuple)."""
        key = tuple(int(d) for d in engine.example_shape)
        server = Server(engine, config=self._server_config)
        now = self._clock()
        with self._lock:
            if self._closed:
                server.close()
                server.join()
                raise ServingUnavailable("router is closed")
            if key in self._engines:
                server.close()
                server.join()
                raise ValueError(f"shape {key} already has a live engine")
            self._engines[key] = _Entry(key, engine, server, now)
            n = len(self._engines)
        trace.metrics.gauge("router_engines", n)
        trace.instant(
            "router_engine_added", shape=list(key), label=engine.label,
            engines=n,
        )
        _logger.info(
            "router %s: engine %s live for shape %s (%d engine(s))",
            self.label, engine.label, key, n,
        )
        return key

    def replace_engine(self, engine: ServingEngine, *, why: str = "engine swap") -> tuple:
        """ATOMICALLY swap the engine serving ``engine.example_shape``:
        the replacement registers under ONE routing-table update
        (add-then-retire), so a request arriving at any instant routes to
        the incumbent or the successor — a retire-then-add sequence would
        open a window where a continuously-servable shape answers a
        transient ``RetryLater``.  The incumbent (when present) drains
        AFTER it is unrouted (:meth:`_retire_entry`: every in-flight
        future resolves, zero request loss); with no incumbent this
        degrades to :meth:`add_engine`.  Mix accounting (``routes``,
        ``last_routed``) carries over so the idle-retire clock does not
        restart on a swap.  Returns the routing key."""
        key = tuple(int(d) for d in engine.example_shape)
        with self._lock:
            old = self._engines.get(key)
            # SLO trackers and drift monitors unregister BY LABEL: a
            # same-label successor would be unregistered by the
            # incumbent's retirement.  Rename BEFORE the Server below
            # registers the SLO tracker.
            if old is not None and engine.label == old.engine.label:
                engine.label = f"{old.engine.label}@swap"
        server = Server(engine, config=self._server_config)
        now = self._clock()
        with self._lock:
            if self._closed:
                server.close()
                server.join()
                raise ServingUnavailable("router is closed")
            old = self._engines.get(key)
            entry = _Entry(key, engine, server, now)
            if old is not None:
                entry.routes = old.routes
                entry.last_routed = old.last_routed
                self.stats.replaces += 1
            self._engines[key] = entry
            n = len(self._engines)
        trace.metrics.gauge("router_engines", n)
        trace.instant(
            "router_engine_added", shape=list(key), label=engine.label,
            engines=n, replaced=old.engine.label if old is not None else None,
        )
        if old is not None:
            self._retire_entry(old, why=why)
        _logger.info(
            "router %s: engine %s %s for shape %s (%s)",
            self.label, engine.label,
            "replaced " + old.engine.label if old is not None else "live",
            key, why,
        )
        return key

    def engines(self) -> dict:
        """shape -> engine label of every live engine (routing table
        snapshot)."""
        with self._lock:
            return {k: e.engine.label for k, e in self._engines.items()}

    def server_for(self, shape) -> Server:
        """The live :class:`~.serve.Server` batching ``shape``'s requests
        (stats/SLO introspection; raises :class:`NoRouteForShape` when the
        shape has no engine)."""
        key = tuple(int(d) for d in shape)
        with self._lock:
            entry = self._engines.get(key)
        if entry is None:
            raise NoRouteForShape(
                f"router {self.label}: no engine serves shape {key}"
            )
        return entry.server

    # -- the request path -----------------------------------------------------

    def submit(self, x) -> ServeFuture:
        """Route one request to the engine serving its shape.  Raises the
        shape family's typed errors: ``MalformedRequest`` (bad payload),
        :class:`RetryLater` (backpressure: shape not warm yet / admission
        out of headroom), :class:`NoRouteForShape` (no factory)."""
        t0 = time.perf_counter()
        arr = np.asarray(x)
        key = tuple(int(d) for d in arr.shape)
        now = self._clock()
        with self._lock:
            if self._closed:
                raise ServingUnavailable("router is closed")
            entry = self._engines.get(key)
            if entry is not None:
                entry.last_routed = now
                entry.routes += 1
                self.stats.routes += 1
        if entry is not None:
            # The router's OWN cost on the hot path: table lookup + mix
            # bookkeeping, measured before the engine's batcher takes over.
            trace.metrics.observe(
                "router_route_overhead_us", (time.perf_counter() - t0) * 1e6
            )
            trace.metrics.inc("router_routes")
            try:
                fut = entry.server.submit(arr)
            except ServingUnavailable:
                # Retired under our feet (the entry was grabbed just before
                # the sweep unrouted it): degrade to the miss path — typed
                # backpressure or a fresh warm, never a dead-engine error
                # for a shape the router still claims to serve.
                return self._miss(arr, key, self._clock())
            self._maybe_adapt(now)
            return fut
        fut = self._miss(arr, key, now)
        self._maybe_adapt(now)
        return fut

    def predict(self, x, timeout: float = 30.0):
        """Blocking convenience: ``submit`` + ``result``, absorbing
        :class:`RetryLater` backpressure by honoring the retry hint until
        ``timeout`` — what a well-behaved wire client does."""
        end = time.monotonic() + timeout
        while True:
            try:
                return self.submit(x).result(max(0.0, end - time.monotonic()))
            except RetryLater as e:
                if time.monotonic() + e.retry_after_s >= end:
                    raise
                time.sleep(e.retry_after_s)

    def _miss(self, arr: np.ndarray, key: tuple, now: float):
        warm_me = False
        with self._lock:
            if self._closed:
                raise ServingUnavailable("router is closed")
            entry = self._engines.get(key)
            if entry is not None:  # lost a warm race — the engine is there
                entry.last_routed = now
                entry.routes += 1
                self.stats.routes += 1
            else:
                self.stats.misses += 1
                trace.metrics.inc("router_misses")
                if self._factory is None:
                    self.stats.no_route += 1
                    raise NoRouteForShape(
                        f"router {self.label}: no engine serves shape {key} "
                        "and no engine factory is configured"
                    )
                dq = self._misses.setdefault(key, deque())
                dq.append(now)
                cutoff = now - self.config.mix_window_s
                while dq and dq[0] < cutoff:
                    dq.popleft()
                hot = len(dq) >= self.config.warm_threshold
                if hot and key not in self._warming:
                    self._warming.add(key)
                    warm_me = True
                elif not hot:
                    self.stats.rejected += 1
                    trace.metrics.inc("router_retry_later")
                    raise RetryLater(
                        f"router {self.label}: shape {key} has no warm "
                        f"engine yet ({len(dq)}/{self.config.warm_threshold} "
                        "recent requests) — retry",
                        self.config.retry_after_s,
                    )
                else:  # another thread is mid-warm for this shape
                    self.stats.rejected += 1
                    trace.metrics.inc("router_retry_later")
                    raise RetryLater(
                        f"router {self.label}: an engine for shape {key} "
                        "is warming — retry",
                        self.config.retry_after_s,
                    )
        if entry is not None:
            return entry.server.submit(arr)
        try:
            return self._warm_and_submit(arr, key, now)
        finally:
            with self._lock:
                self._warming.discard(key)
                self._warm_reserved.pop(key, None)

    # -- warm add (the closed loop's grow side) -------------------------------

    def _warm_and_submit(self, arr: np.ndarray, key: tuple, now: float):
        # At the engine ceiling the only way to warm is to free a slot:
        # retire the idlest engine IF it has stopped earning traffic —
        # the shape mix genuinely shifted, so the slot follows it.
        evict = None
        with self._lock:
            if len(self._engines) >= self.config.max_engines:
                idlest = min(
                    self._engines.values(), key=lambda e: e.last_routed
                )
                if (
                    now - idlest.last_routed >= self.config.mix_window_s
                    and len(self._engines) > self.config.min_engines
                ):
                    evict = self._engines.pop(idlest.key)
                else:
                    self.stats.rejected += 1
                    trace.metrics.inc("router_retry_later")
                    raise RetryLater(
                        f"router {self.label}: at the engine ceiling "
                        f"({self.config.max_engines}) with every engine "
                        "still earning traffic — retry",
                        self.config.retry_after_s,
                    )
        if evict is not None:
            self._retire_entry(evict, why="evicted for a hotter shape")
        with trace.span(
            "router.warm", cat="serve", shape=list(key), label=self.label
        ):
            engine = self._factory(key, arr.dtype)
        admitted, verdict = self._cross_admission(key, engine)
        with self._lock:
            self.admissions.append(verdict)
            del self.admissions[:-16]  # bounded ledger
        if not admitted:
            with self._lock:
                self.stats.admission_denied += 1
                self.stats.rejected += 1
            counters.record(
                "router_admission_denied",
                f"router {self.label}: warm add for shape {key} denied — "
                f"{verdict['reason']}",
            )
            raise RetryLater(
                f"router {self.label}: no HBM headroom to warm an engine "
                f"for shape {key} ({verdict['reason']}) — retry",
                self.config.retry_after_s,
            )
        self.add_engine(engine)
        with self._lock:
            self.stats.warm_adds += 1
            self._misses.pop(key, None)
            entry = self._engines.get(key)
            if entry is not None:
                entry.last_routed = self._clock()
                entry.routes += 1
                self.stats.routes += 1
        trace.metrics.inc("router_warm_adds")
        trace.instant(
            "router_engine_warmed", shape=list(key), label=engine.label
        )
        if entry is None:  # pragma: no cover — add_engine just inserted it
            raise ServingUnavailable("router closed during warm add")
        return entry.server.submit(arr)

    def _engine_peak_bytes(self, engine: ServingEngine) -> int:
        """The engine's steady-state HBM claim: the largest LIVE bucket's
        planned total (argument+temp+output−alias), from the very
        ``plan_program`` preflight that admitted it.  Unanalyzed plans (no
        budget known at build) fall back to an analytic floor: padded
        batch in + out bytes of the largest bucket."""
        peak = 0
        live = set(engine.buckets())
        for bucket, plan in engine.memory_plans.items():
            if bucket not in live:
                continue
            if plan.analyzed and plan.total_bytes:
                peak = max(peak, int(plan.total_bytes))
            else:
                row = int(
                    np.prod(engine.example_shape, dtype=np.int64)
                    * engine.example_dtype.itemsize
                ) if engine.example_shape else engine.example_dtype.itemsize
                peak = max(peak, 2 * bucket * row)
        return peak

    def _cross_admission(
        self, key: tuple, new_engine: ServingEngine
    ) -> tuple[bool, dict]:
        """The missing cross-engine sum over the per-engine preflights:
        live engines' peak-bucket bytes, OTHER in-flight warm adds'
        reserved bytes, and the candidate's must together fit the shared
        HBM budget (``core.memory.hbm_budget``; unknown budget admits with
        the reason recorded, exactly like ``plan_program``).  An admitted
        candidate RESERVES its bytes under the same lock acquisition, so
        two concurrent warms for different shapes cannot both pass against
        the same headroom; the reservation clears once the engine is in
        the routing table (the ``_miss`` finally).

        A mesh-anchored router budgets against the CURRENT anchor mesh's
        smallest chip (``min_chip_budget``): after a re-anchor the sum
        re-runs against the surviving topology — a budget computed on the
        dead mesh would over-admit (ISSUE 16)."""
        mesh = self._mesh
        if mesh is not None:
            budget, _ = kmem.min_chip_budget(mesh)
        else:
            budget = kmem.hbm_budget()
        candidate = self._engine_peak_bytes(new_engine)
        with self._lock:
            resident = sum(
                self._engine_peak_bytes(e.engine)
                for e in self._engines.values()
            )
            reserved = sum(
                v for k, v in self._warm_reserved.items() if k != key
            )
            verdict = {
                "label": new_engine.label,
                "resident_bytes": int(resident),
                "reserved_bytes": int(reserved),
                "candidate_bytes": int(candidate),
                "budget_bytes": int(budget) if budget is not None else None,
            }
            if budget is None:
                verdict.update(
                    admitted=True,
                    reason=(
                        "no HBM budget known — cross-engine admission "
                        "skipped"
                    ),
                )
                return True, verdict
            admitted = resident + reserved + candidate <= budget
            if admitted:
                self._warm_reserved[key] = candidate
            verdict.update(
                admitted=admitted,
                reason=(
                    f"{resident + reserved + candidate} bytes across "
                    f"engines vs budget {budget}"
                ),
            )
        trace.instant(
            "router_admission",
            admitted=admitted,
            resident_bytes=int(resident),
            reserved_bytes=int(reserved),
            candidate_bytes=int(candidate),
            budget_bytes=int(budget),
        )
        return admitted, verdict

    # -- retire (the closed loop's shrink side) -------------------------------

    def _maybe_adapt(self, now: float) -> None:
        if now - self._last_adapt < self.config.adapt_interval_s:
            return
        with self._lock:
            if self._adapting or self._closed:
                return
            if now - self._last_adapt < self.config.adapt_interval_s:
                return
            self._adapting = True
            self._last_adapt = now
        threading.Thread(
            target=self._adapt_bg, name="keystone-router-adapt", daemon=True
        ).start()

    def _adapt_bg(self) -> None:
        try:
            self.adapt()
        except Exception:  # noqa: BLE001 — the sweep must not die silently
            _logger.exception("router adapt sweep failed")
        finally:
            self._adapting = False

    def adapt(self) -> dict:
        """One retire sweep: unroute every engine idle past
        ``retire_after_s`` (down to ``min_engines``), drain it, close it,
        unregister its SLO tracker.  Returns the actions taken (tests and
        the bench call this directly; the submit path runs it on a
        background thread every ``adapt_interval_s``)."""
        now = self._clock()
        retired: list[_Entry] = []
        with self._lock:
            if self._closed:
                return {"retired": []}
            idle_first = sorted(
                self._engines.values(), key=lambda e: e.last_routed
            )
            for entry in idle_first:
                if len(self._engines) <= self.config.min_engines:
                    break
                if now - entry.last_routed >= self.config.retire_after_s:
                    del self._engines[entry.key]
                    retired.append(entry)
        for entry in retired:
            self._retire_entry(entry, why="stopped earning traffic")
        return {"retired": [list(e.key) for e in retired]}

    # -- surviving-mesh re-anchor (ISSUE 16) ----------------------------------

    def reanchor(self, mesh, *, why: str = "device loss") -> dict:
        """Hot-swap every live engine onto ``mesh`` — the surviving-mesh
        re-anchor after device loss or per-chip admission denial.

        Zero request loss, the PR-12 swap invariant: each replacement
        engine is built and REGISTERED before its predecessor is unrouted,
        so requests route to one or the other at every instant; the
        predecessor then drains (every outstanding future resolves) and
        closes through the same :meth:`_retire_entry` path a mix-driven
        retire uses.  A shape whose rebuild fails on every tier keeps its
        OLD engine serving (degraded, not dead) and lands in the record's
        ``failed`` list.  The whole event is counted ``mesh_reanchor``
        (trace fault instant + flight-recorder postmortem) and the record
        is surfaced as ``last_reanchor`` in :meth:`record`.
        """
        t0 = time.perf_counter()
        if self._factory is None:
            raise ServingUnavailable(
                f"router {self.label}: cannot re-anchor without an engine "
                "factory"
            )
        if isinstance(self._factory, MeshEngineFactory):
            self._factory.set_mesh(mesh)
        with self._lock:
            if self._closed:
                raise ServingUnavailable("router is closed")
            self._mesh = mesh
            old_entries = list(self._engines.values())
        desc = kmesh.mesh_desc(mesh) if mesh is not None else "single-device"
        swapped: list[dict] = []
        failed: list[dict] = []
        for old in old_entries:
            try:
                with trace.span(
                    "router.reanchor", cat="serve", shape=list(old.key),
                    label=self.label, mesh=desc,
                ):
                    engine = self._factory(old.key, old.engine.example_dtype)
            except ServeError as e:
                failed.append({
                    "shape": list(old.key),
                    "error": f"{type(e).__name__}: {e}",
                })
                _logger.warning(
                    "router %s: re-anchor of shape %s onto mesh %s failed "
                    "(%s) — old engine keeps serving",
                    self.label, old.key, desc, e,
                )
                continue
            if engine.label == old.engine.label:
                # SLO trackers and drift monitors unregister BY LABEL when
                # the predecessor retires — the replacement must not share
                # its name or it gets unregistered with the corpse.
                engine.label = f"{old.engine.label}@{desc}"
            server = Server(engine, config=self._server_config)
            now = self._clock()
            with self._lock:
                stale = self._closed or self._engines.get(old.key) is not old
                if not stale:
                    entry = _Entry(old.key, engine, server, now)
                    entry.routes = old.routes
                    entry.last_routed = old.last_routed
                    self._engines[old.key] = entry
            if stale:
                # Retired/replaced mid-build (or the router closed) — do
                # not resurrect the shape; discard the fresh server.
                server.close()
                server.join()
                telemetry.unregister_slo(engine.label)
                knum.unregister_drift(engine.label)
                continue
            trace.instant(
                "router_engine_added", shape=list(old.key),
                label=engine.label, mesh=desc,
            )
            self._retire_entry(
                old, why=f"re-anchored onto mesh {desc} ({why})"
            )
            swapped.append({"shape": list(old.key), "label": engine.label})
        wall = time.perf_counter() - t0
        rec = {
            "mesh": desc,
            "why": why,
            "swapped": swapped,
            "failed": failed,
            "reshard_wall_s": round(wall, 6),
        }
        with self._lock:
            self._last_reanchor = rec
        counters.record(
            "mesh_reanchor",
            f"router {self.label}: {len(swapped)} engine(s) re-anchored "
            f"onto mesh {desc} in {wall:.3f}s ({why}; "
            f"{len(failed)} failed)",
        )
        trace.instant(
            "router_reanchor", mesh=desc, swapped=len(swapped),
            failed=len(failed), wall_s=round(wall, 6), why=why,
        )
        _logger.info(
            "router %s: re-anchored %d engine(s) onto mesh %s in %.3fs "
            "(%s; %d failed)",
            self.label, len(swapped), desc, wall, why, len(failed),
        )
        return rec

    def _retire_entry(self, entry: _Entry, why: str) -> None:
        """Graceful engine retirement: the entry is ALREADY unrouted (new
        requests for its shape go down the miss path), so draining resolves
        every outstanding future before the server closes — zero request
        loss across the swap."""
        drained = entry.server.drain(self.config.drain_timeout_s)
        if not drained:
            _logger.warning(
                "router %s: engine %s did not drain in %.1fs — closing "
                "anyway (stragglers answer ServingUnavailable, typed)",
                self.label, entry.engine.label, self.config.drain_timeout_s,
            )
        entry.server.close()
        entry.server.join()
        telemetry.unregister_slo(entry.engine.label)
        # A retired engine's drift monitor must leave the live numerics
        # surface with it (its history belongs to the records that
        # captured it, not to every future /statusz snapshot).
        knum.unregister_drift(entry.engine.label)
        with self._lock:
            self.stats.retires += 1
            n = len(self._engines)
        trace.metrics.inc("router_engine_retired")
        trace.metrics.gauge("router_engines", n)
        trace.instant(
            "router_engine_retired", shape=list(entry.key),
            label=entry.engine.label, why=why, drained=drained,
            routes=entry.routes, engines=n,
        )
        _logger.info(
            "router %s: retired engine %s (%s; %d requests routed, "
            "drained=%s)",
            self.label, entry.engine.label, why, entry.routes, drained,
        )

    # -- lifecycle / records --------------------------------------------------

    def drain(self, timeout: float = 30.0) -> bool:
        """Drain every live engine (all outstanding futures resolve)."""
        end = time.monotonic() + timeout
        with self._lock:
            entries = list(self._engines.values())
        ok = True
        for entry in entries:
            ok &= entry.server.drain(max(0.0, end - time.monotonic()))
        return ok

    def close(self) -> None:
        """Close every engine's server (pending requests answer
        ``ServingUnavailable``) and stop routing.  Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            entries = list(self._engines.values())
            self._engines.clear()
        for entry in entries:
            entry.server.close()
            entry.server.join()
            telemetry.unregister_slo(entry.engine.label)
            knum.unregister_drift(entry.engine.label)
        telemetry.unregister_statusz(
            f"router:{self.label}", self._statusz_provider
        )
        trace.metrics.gauge("router_engines", 0)

    def __enter__(self) -> "ShapeRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def record(self) -> dict:
        """JSON-able router summary for bench/serving records: the live
        routing table, lifetime stats (routes/misses/warm_adds/retires),
        and the admission ledger."""
        now = self._clock()
        with self._lock:
            engines = {
                "x".join(map(str, k)) or "scalar": {
                    "label": e.engine.label,
                    "live_buckets": list(e.engine.buckets()),
                    "routes": e.routes,
                    "idle_seconds": round(now - e.last_routed, 3),
                    # Output-drift verdict (ISSUE 15): the engine's live
                    # divergence vs its fit-time baseline, None when no
                    # baseline was armed.
                    "drift": (
                        e.engine.drift.record()
                        if e.engine.drift is not None
                        else None
                    ),
                }
                for k, e in self._engines.items()
            }
            stats = self.stats.record()
            admissions = list(self.admissions)
            last_reanchor = self._last_reanchor
            mesh = self._mesh
        out = {
            "label": self.label,
            "mesh": kmesh.mesh_desc(mesh) if mesh is not None else None,
            "config": self.config.record(),
            "engines": engines,
            "stats": stats,
            "admissions": admissions,
            "last_reanchor": last_reanchor,
        }
        from . import profiler as kprof

        if kprof.enabled():
            # Device cost attribution (ISSUE 14): with the profiler on,
            # the router record carries the per-program MFU ledger — the
            # per-shape serve buckets' roofline positions land in every
            # serving artifact that embeds the router.
            out["profiler"] = kprof.ledger_record()
        return out


# -- multi-host fleet front-end (ISSUE 17) ------------------------------------


class HostFleet:
    """The wire front-end over N HOST-LOCAL routers: one
    :class:`~.wire.WireClient` per fleet member, requests spread
    round-robin, and a member whose socket dies is declared lost (counted
    ``fleet_host_lost``, postmortem-linked) with the request REISSUED to a
    survivor — a host loss costs the fleet capacity, never an answer.

    This is the serving half of the multi-host story: engines never span
    hosts (``ServingEngine`` refuses a process-spanning mesh), so scale-out
    is N independent ``ShapeRouter`` + ``WireServer`` pairs — one per host,
    each anchored on its :func:`~..parallel.mesh.host_local_mesh` — fronted
    by this class.  Predictions are pure, so reissuing an in-flight request
    to a survivor is exact, not at-least-once-with-drift; a request only
    fails when NO host is left (typed :class:`ServingUnavailable`).

    Thread-safe: each member's client socket is guarded by its own lock, so
    concurrent callers fan out across members instead of serializing."""

    def __init__(self, endpoints, *, label: str = "fleet", timeout: float = 30.0):
        if not endpoints:
            raise ValueError("HostFleet needs at least one endpoint")
        self.label = label
        self.timeout = float(timeout)
        self._hosts = []
        for ep in endpoints:
            if isinstance(ep, str):
                host, _, port = ep.rpartition(":")
                ep = (host or "127.0.0.1", int(port))
            self._hosts.append(
                {
                    "endpoint": (str(ep[0]), int(ep[1])),
                    "client": None,
                    "lock": threading.Lock(),
                    "alive": True,
                    "requests": 0,
                    "reissued": 0,
                }
            )
        self._rr = 0
        self._rr_lock = threading.Lock()
        self.lost_hosts = 0
        self._collector = None
        trace.instant(
            "fleet.up",
            label=label,
            hosts=[list(h["endpoint"]) for h in self._hosts],
        )

    def _client(self, h):
        from . import wire

        if h["client"] is None:
            h["client"] = wire.WireClient(
                h["endpoint"][0], h["endpoint"][1], timeout=self.timeout
            )
        return h["client"]

    def _mark_lost(self, h, why: str) -> None:
        if not h["alive"]:
            return
        h["alive"] = False
        self.lost_hosts += 1
        try:
            if h["client"] is not None:
                h["client"].close()
        finally:
            h["client"] = None
        counters.record(
            "fleet_host_lost", f"{self.label}: {h['endpoint']}: {why}"
        )

    def alive_hosts(self) -> list:
        return [h["endpoint"] for h in self._hosts if h["alive"]]

    def attach_collector(self, collector) -> None:
        """Self-register the whole fleet with a
        :class:`~.fleetobs.FleetCollector`: every current member becomes
        an observed obs agent (the serving socket doubles as the obs
        endpoint), and members re-admitted later via :meth:`reattach`
        register too."""
        self._collector = collector
        for rank, h in enumerate(self._hosts):
            collector.register(h["endpoint"], rank=rank)

    def predict(self, arr, timeout: float | None = None):
        """Answer one request through some live host.  A member that dies
        mid-request (reset, closed socket, silence past the deadline) is
        declared lost and the SAME request is reissued to the next member;
        typed remote errors (the server answering "no") propagate — they
        are answers, not host deaths."""
        from . import wire

        budget = timeout if timeout is not None else self.timeout
        tried = 0
        n = len(self._hosts)
        while True:
            live = [h for h in self._hosts if h["alive"]]
            if not live:
                raise ServingUnavailable(
                    f"fleet {self.label!r}: all {n} host(s) lost"
                )
            with self._rr_lock:
                h = live[self._rr % len(live)]
                self._rr += 1
            try:
                with h["lock"]:
                    client = self._client(h)
                    h["requests"] += 1
                    return client.predict(arr, timeout=budget)
            except wire.WireRemoteError:
                raise  # a typed answer from a live host
            except (OSError, TimeoutError, wire.WireProtocolError) as e:
                self._mark_lost(h, f"{type(e).__name__}: {e}")
                tried += 1
                if tried > n:  # pragma: no cover - every host died
                    raise ServingUnavailable(
                        f"fleet {self.label!r}: no host answered: {e}"
                    ) from e
                h["reissued"] += 1  # this member's loss forced a reissue

    def reattach(self, endpoint) -> None:
        """Re-admit a (restarted) member at ``endpoint`` — the scale-back-up
        half of elasticity.  New endpoint, new member; known endpoint,
        revived in place."""
        if isinstance(endpoint, str):
            host, _, port = endpoint.rpartition(":")
            endpoint = (host or "127.0.0.1", int(port))
        endpoint = (str(endpoint[0]), int(endpoint[1]))
        for h in self._hosts:
            if h["endpoint"] == endpoint:
                h["alive"] = True
                h["client"] = None
                trace.instant("fleet.reattach", endpoint=list(endpoint))
                if self._collector is not None:
                    self._collector.register(endpoint)
                return
        self._hosts.append(
            {
                "endpoint": endpoint,
                "client": None,
                "lock": threading.Lock(),
                "alive": True,
                "requests": 0,
                "reissued": 0,
            }
        )
        trace.instant("fleet.reattach", endpoint=list(endpoint))
        if self._collector is not None:
            self._collector.register(endpoint, rank=len(self._hosts) - 1)

    def record(self) -> dict:
        return {
            "label": self.label,
            "hosts": [
                {
                    "endpoint": list(h["endpoint"]),
                    "alive": h["alive"],
                    "requests": h["requests"],
                    "reissued": h["reissued"],
                }
                for h in self._hosts
            ],
            "lost_hosts": self.lost_hosts,
        }

    def close(self) -> None:
        for h in self._hosts:
            with h["lock"]:
                if h["client"] is not None:
                    try:
                        h["client"].close()
                    except OSError:  # pragma: no cover
                        pass
                    h["client"] = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
