"""Low-latency serving: fused AOT inference, dynamic request batching, and
online SLO observability.

Every other subsystem in this repo optimizes the throughput of *fit*; this
module serves a *fitted* pipeline under request traffic — the
"millions of users" half of the ROADMAP north star.  KeystoneML pipelines
were deploy-once/apply-many artifacts (fit on the cluster, apply forever);
the TensorFlow paper (PAPERS.md: 1605.08695) shows what the apply-forever
half needs to be fast: ONE compiled program, parameters warm-loaded once,
requests batched.  tf.data (PAPERS.md: 2101.12127) supplies the
deadline-aware pipelined feeding idiom the batcher mirrors.

Three pieces:

* **Fused AOT inference** (:class:`ServingEngine`) — the whole fitted
  apply-chain compiles into one donated-input AOT executable per **batch
  bucket** via the existing ``core.memory.plan_program`` preflight, so
  every bucket is admission-checked against the HBM budget before it can
  ever OOM a live endpoint, and its ``memory_analysis`` breakdown is
  recorded (``engine.memory_plans``).  Fitted state warm-loads from a
  ``core.checkpoint`` artifact (:func:`load_engine` measures the
  fresh-process cold start: restore seconds, per-bucket compile seconds,
  first-inference warmup).  Buckets are BATCH-size buckets over one fixed
  request shape — the static-shape discipline XLA wants; a workload with
  several request shapes runs one engine per shape, exactly like the
  ingest stream's shape buckets.
* **Dynamic request batcher** (:class:`Server`) — a thread-safe request
  queue feeding bucket-sized micro-batches with deadline-aware flush:
  a batch goes out when it reaches the largest bucket OR when the OLDEST
  pending request has waited ``max_wait_ms``, whichever first.  Remainder
  batches pad up to the nearest bucket (pad rows are sliced off before
  answering — row-wise programs never mix rows, so padding changes
  latency, not results).  H2D is double-buffered with the ``core.ingest``
  two-in-flight idiom: the assembler thread dispatches ``device_put`` for
  micro-batch *i+1* while the executor thread runs batch *i*, and only
  the executor ever blocks on device work.  Each request is answered with
  its own output slice, in arrival order.
* **Observability + typed failure** — every request gets an id at
  ``submit`` that rides through its whole lifecycle (a ``serve.submit``
  instant, request-id ranges on the per-micro-batch ``serve.h2d`` /
  ``serve.execute`` / ``serve.d2h`` spans, and a per-request
  ``serve.request`` span), and a per-phase latency decomposition —
  queue-wait / H2D / device-wait / execute / D2H / answer / pad overhead
  — lands on ``ServeFuture.phases`` and aggregates in ``serve_bench``'s
  ``phase_breakdown``.  Each ``Server`` registers a live SLO tracker
  (``core.telemetry``: rolling p50/p99/QPS + error-budget burn rate
  against ``KEYSTONE_SERVE_SLO_MS``), batcher state exports into the
  ``trace.metrics`` registry (flush-reason counters, bucket retirements,
  occupancy), and the typed-or-equal invariant extends online: a
  malformed request dies at ``submit`` with a counted
  :class:`MalformedRequest` and NEVER enters a batch (no poisoned
  batchmates); a burst OOM degrades to a smaller bucket (counted
  ``serve_burst_oom``) and re-answers the same requests — never a silent
  wrong answer; a dead endpoint answers :class:`ServingUnavailable`, not
  a bare traceback.

Env knobs (documented in README's ``KEYSTONE_*`` table):

* ``KEYSTONE_SERVE_BUCKETS`` — comma-separated batch buckets (default
  ``1,4,16,64``).
* ``KEYSTONE_SERVE_MAX_BATCH`` — cap/extend the largest bucket.
* ``KEYSTONE_SERVE_MAX_WAIT_MS`` — deadline-aware flush budget (default
  ``5``).
* ``KEYSTONE_SERVE_EAGER_FLUSH`` — ``0`` disables the opportunistic idle
  flush (a micro-batch dispatches as soon as the device pipeline is idle,
  without waiting out ``max_wait_ms``; the TF-Serving batch-scheduler
  discipline — the deadline only governs waiting while the device is busy).

Bucket parity: XLA may emit a DIFFERENT reduction order for the same
row-wise program at different batch sizes (measured here: the batch-1
matmul takes a gemv path whose rounding differs from the gemm the larger
buckets and the eager oracle share).  A bucket whose rows are not
bit-identical to the offline apply would silently break the "served answer
== pipeline(x)" contract, so :meth:`ServingEngine.warmup` doubles as a
PARITY CHECK: every bucket executes a deterministic probe batch and any
bucket whose rows differ from the eager oracle is dropped (counted
``serve_bucket_parity_dropped``) — unless NO bucket passes, in which case
the engine serves but says so (``parity_ok=False``, counted once) rather
than refusing service.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from typing import Any, Callable, Sequence

import numpy as np

from . import memory as kmem
from . import numerics as knum
from . import profiler as kprof
from . import telemetry
from . import trace
from .resilience import counters

_logger = logging.getLogger("keystone_tpu.serve")

BUCKETS_ENV = "KEYSTONE_SERVE_BUCKETS"
MAX_BATCH_ENV = "KEYSTONE_SERVE_MAX_BATCH"
MAX_WAIT_ENV = "KEYSTONE_SERVE_MAX_WAIT_MS"
EAGER_FLUSH_ENV = "KEYSTONE_SERVE_EAGER_FLUSH"

DEFAULT_BUCKETS = (1, 4, 16, 64)
DEFAULT_MAX_WAIT_MS = 5.0

#: Micro-batches in flight between the assembler and the executor — the
#: consumed batch plus the one whose H2D overlaps it (the core.ingest
#: DEVICE_BUFFERS idiom, applied to the request path).
INFLIGHT_BATCHES = 2

#: Every blocking wait polls at this period so stop flags and the
#: resilience.deadline SIGALRM are always observed (same discipline as the
#: ingest ring).
_POLL_SECONDS = 0.05


class ServeError(RuntimeError):
    """Base of the serving subsystem's typed failures."""


class MalformedRequest(ServeError, ValueError):
    """A request that cannot enter a batch: wrong shape, uncastable dtype,
    or non-finite payload.  Raised at ``submit`` time — the request is
    REJECTED (counted ``serve_malformed_request``) before it can poison
    the micro-batch its batchmates ride in."""


class ServingUnavailable(ServeError):
    """The endpoint cannot answer: every batch bucket OOMed away, or the
    server was closed with requests still pending.  Typed — a dead
    endpoint is an operable condition, never a bare traceback."""


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        val = float(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r} is not a number") from None
    if val < 0:
        raise ValueError(f"{name}={raw!r} must be >= 0")
    return val


def _parse_buckets(raw: str) -> tuple[int, ...]:
    try:
        vals = tuple(sorted({int(tok) for tok in raw.split(",") if tok.strip()}))
    except ValueError:
        raise ValueError(
            f"{BUCKETS_ENV}={raw!r}: expected comma-separated integers"
        ) from None
    if not vals or any(v < 1 for v in vals):
        raise ValueError(f"{BUCKETS_ENV}={raw!r}: buckets must be >= 1")
    return vals


@dataclasses.dataclass
class ServeConfig:
    """Knob set of one serving endpoint (env-seeded via :meth:`from_env`)."""

    #: ascending batch-size buckets; one AOT executable compiles per bucket.
    buckets: tuple = DEFAULT_BUCKETS
    #: deadline-aware flush: a micro-batch goes out when the OLDEST pending
    #: request has waited this long, even if the largest bucket isn't full.
    max_wait_ms: float = DEFAULT_MAX_WAIT_MS
    #: donate the request batch buffer into the compiled program (the
    #: engine owns the freshly-transferred micro-batch, so donation is
    #: always safe and halves the inference working set).
    donate: bool = True
    #: opportunistic idle flush: when the device pipeline is idle a pending
    #: micro-batch dispatches IMMEDIATELY instead of aging toward
    #: ``max_wait_ms`` — the deadline then only governs waiting while the
    #: device is busy (where waiting buys occupancy).  Disable for strict
    #: two-trigger (full-or-deadline) flushing.
    eager_flush: bool = True

    def __post_init__(self):
        buckets = tuple(sorted({int(b) for b in self.buckets}))
        if not buckets or any(b < 1 for b in buckets):
            raise ValueError(f"buckets must all be >= 1, got {self.buckets}")
        self.buckets = buckets
        if self.max_wait_ms < 0:
            raise ValueError(
                f"max_wait_ms must be >= 0, got {self.max_wait_ms}"
            )

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    @classmethod
    def from_env(cls, **overrides) -> "ServeConfig":
        """``KEYSTONE_SERVE_BUCKETS`` / ``KEYSTONE_SERVE_MAX_BATCH`` /
        ``KEYSTONE_SERVE_MAX_WAIT_MS``, any field overridable by keyword."""
        cfg: dict = {}
        raw = os.environ.get(BUCKETS_ENV, "").strip()
        buckets = _parse_buckets(raw) if raw else DEFAULT_BUCKETS
        mb = os.environ.get(MAX_BATCH_ENV, "").strip()
        if mb:
            cap = int(mb)
            if cap < 1:
                raise ValueError(f"{MAX_BATCH_ENV}={mb!r} must be >= 1")
            buckets = tuple(b for b in buckets if b < cap) + (cap,)
        cfg["buckets"] = buckets
        cfg["max_wait_ms"] = _env_float(MAX_WAIT_ENV, DEFAULT_MAX_WAIT_MS)
        cfg["eager_flush"] = (
            os.environ.get(EAGER_FLUSH_ENV, "").strip() != "0"
        )
        cfg.update({k: v for k, v in overrides.items() if v is not None})
        return cls(**cfg)

    def record(self) -> dict:
        return {
            "buckets": list(self.buckets),
            "max_wait_ms": self.max_wait_ms,
            "donate": self.donate,
            "eager_flush": self.eager_flush,
        }


# -- fused AOT inference ------------------------------------------------------


class ServingEngine:
    """One fitted pipeline compiled into per-bucket AOT inference programs.

    ``pipe`` is any fitted Transformer/Pipeline over batches (a registered
    pytree: its fitted arrays become real program ARGUMENTS, not baked
    constants, so the same weights buffer feeds every bucket executable).
    ``example`` fixes one request's shape/dtype — a host array or
    ``jax.ShapeDtypeStruct`` WITHOUT the batch axis.

    Every bucket preflights through ``core.memory.plan_program`` (the same
    admission control the solvers use): the request batch argument is
    DONATED, the breakdown is recorded in ``memory_plans``, and a bucket
    denied by admission never compiles into the endpoint — it is dropped
    with a counted ``serve_bucket_denied`` (the smallest bucket is the
    floor and is kept even when denied, exactly like ``run_ladder``'s
    floor tier).  A bucket that still hits RESOURCE_EXHAUSTED under burst
    traffic at runtime is retired (counted ``serve_burst_oom``) and its
    requests re-run through smaller buckets — degradation, never a silent
    wrong answer.
    """

    def __init__(
        self,
        pipe,
        example,
        *,
        config: ServeConfig | None = None,
        label: str = "pipeline",
        warmup: bool = True,
        mesh=None,
    ):
        import jax

        self._jax = jax
        #: the eager parity/offline oracle always applies the ORIGINAL
        #: object — mixing mesh-committed params into the eager apply
        #: would let placement errors masquerade as parity failures.
        self._oracle_pipe = pipe
        if mesh is not None:
            from ..parallel.mesh import host_local_mesh, mesh_spans_processes

            if mesh_spans_processes(mesh):
                # Serving never spans hosts: a request answered through a
                # cross-process mesh would need every host's cooperation
                # per request (one slow peer stalls the whole fleet, one
                # dead peer aborts it).  Typed refusal with the fix named
                # — anchor each host's engines on ITS sub-mesh and let the
                # front-end fan requests across hosts.
                raise ServeError(
                    f"serving mesh spans processes — anchor on "
                    f"host_local_mesh(mesh) instead "
                    f"(this host owns {host_local_mesh(mesh).devices.size} "
                    f"of the mesh's devices)"
                )
        self.mesh = mesh
        self._pipe = self._mesh_place(pipe, mesh) if mesh is not None else pipe
        self.label = label
        self.config = config or ServeConfig.from_env()
        self.example_shape = tuple(int(d) for d in example.shape)
        self.example_dtype = np.dtype(example.dtype)
        if self.config.donate:
            self._fn = jax.jit(
                lambda pipe, batch: pipe(batch), donate_argnums=(1,)
            )
        else:
            self._fn = jax.jit(lambda pipe, batch: pipe(batch))
        #: bucket -> MemoryPlan (admission verdict + memory_analysis
        #: breakdown) for EVERY configured bucket, dropped ones included.
        self.memory_plans: dict[int, kmem.MemoryPlan] = {}
        #: bucket -> seconds of the warmup inference (compile+first run
        #: cost a live request never pays).
        self.warmup_seconds: dict[int, float] = {}
        #: bucket -> did its probe rows come back bit-identical to the
        #: eager offline apply (filled by :meth:`warmup`)?
        self.parity: dict[int, bool] = {}
        #: False only when NO bucket passed the parity probe (the engine
        #: serves, but its answers are per-bucket-consistent rather than
        #: verified eager-equal — counted, never silent).
        self.parity_ok: bool = True
        self._exec: dict[int, Any] = {}
        self._lock = threading.Lock()
        #: numerics observatory (ISSUE 15): the output-drift monitor, armed
        #: by :meth:`arm_drift_baseline` when a fit-time reference baseline
        #: exists (load_engine reads it from the checkpoint manifest).
        self.drift: knum.DriftMonitor | None = None
        self._build()
        if warmup:
            self.warmup()

    # -- construction ---------------------------------------------------------

    def _mesh_place(self, pipe, mesh):
        """Pin the fitted state onto the serving mesh.  A ``jax.Array``
        leaf already resident on exactly this mesh's devices keeps its
        SOLVE placement (a mesh fit serves from where it solved — no host
        pull); every other array leaf is placed replicated
        (``autoshard.spec_sharding``) so each bucket program sees
        committed, mesh-consistent parameters."""
        from . import autoshard

        jax = self._jax
        mesh_devs = set(mesh.devices.flat)

        def place(leaf):
            if isinstance(leaf, jax.Array):
                try:
                    if set(leaf.sharding.device_set) == mesh_devs:
                        return leaf
                except Exception:  # noqa: BLE001 — unknown sharding: re-place
                    pass
            elif not isinstance(leaf, (np.ndarray, np.generic)):
                return leaf
            arr = np.asarray(jax.device_get(leaf))
            return jax.device_put(
                arr, autoshard.spec_sharding("replicated", mesh, arr.ndim)
            )

        return jax.tree_util.tree_map(place, pipe)

    def _batch_sharding(self, bucket: int):
        """Layout of one request micro-batch on the serving mesh:
        row-sharded over the data axis when the bucket divides evenly,
        replicated otherwise (small buckets under a wide mesh).  ``None``
        when the engine is meshless."""
        if self.mesh is None:
            return None
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.mesh import DATA_AXIS

        d = int(self.mesh.shape[DATA_AXIS])
        if d > 1 and bucket % d == 0:
            return NamedSharding(self.mesh, P(DATA_AXIS))
        return NamedSharding(self.mesh, P())

    def _batch_struct(self, bucket: int):
        sharding = self._batch_sharding(bucket)
        if sharding is not None:
            return self._jax.ShapeDtypeStruct(
                (bucket, *self.example_shape), self.example_dtype,
                sharding=sharding,
            )
        return self._jax.ShapeDtypeStruct(
            (bucket, *self.example_shape), self.example_dtype
        )

    def _h2d(self, padded: np.ndarray, bucket: int):
        """One micro-batch host->device, onto the serving mesh when one is
        set — the layout the bucket's AOT executable was lowered for."""
        sharding = self._batch_sharding(bucket)
        if sharding is None:
            return self._jax.device_put(padded)
        return self._jax.device_put(padded, sharding)

    def _build(self) -> None:
        for i, bucket in enumerate(self.config.buckets):
            floor = i == 0
            with trace.span(
                "serve.compile", cat="serve", bucket=bucket, label=self.label
            ):
                plan = kmem.plan_program(
                    self._fn,
                    self._pipe,
                    self._batch_struct(bucket),
                    label=f"serve:{self.label}:b{bucket}",
                    require_analysis=True,
                    mesh=self.mesh,
                )
            self.memory_plans[bucket] = plan
            if plan.compiled is None:
                raise ServeError(
                    f"serve:{self.label}: bucket {bucket} failed to "
                    f"compile — {plan.reason}"
                )
            if not plan.admitted and not floor:
                counters.record(
                    "serve_bucket_denied",
                    f"serve:{self.label}: bucket {bucket} denied by HBM "
                    f"admission ({plan.reason}) — endpoint serves without it",
                )
                continue
            if not plan.admitted and floor:
                _logger.warning(
                    "serve:%s: floor bucket %d denied by preflight (%s) but "
                    "nothing is below it — serving anyway",
                    self.label, bucket, plan.reason,
                )
            self._exec[bucket] = plan.compiled
        if not self._exec:  # pragma: no cover — floor is always kept
            raise ServeError(f"serve:{self.label}: no bucket survived admission")

    def _probe_batch(self, rows: int) -> np.ndarray:
        """Deterministic nonzero probe data for the parity check (zeros
        would let a broken program pass by accident)."""
        rng = np.random.default_rng(20260803)
        shape = (rows, *self.example_shape)
        if self.example_dtype.kind in "fc":
            return rng.standard_normal(shape).astype(self.example_dtype)
        info = np.iinfo(self.example_dtype)
        return rng.integers(
            info.min, min(info.max, 255), shape, endpoint=True
        ).astype(self.example_dtype)

    def warmup(self) -> float:
        """Run each live bucket once on a deterministic probe batch — no
        live request ever pays a first-dispatch cost — and VERIFY PARITY:
        each bucket's probe rows must be bit-identical to the eager offline
        apply of the same rows.  A bucket that fails (XLA's batch-1 gemv
        path rounds differently than the shared gemm path, for instance) is
        dropped with a counted ``serve_bucket_parity_dropped``.  If NO
        bucket passes (XLA fuses the whole chain differently than the
        op-by-op eager apply — the Fisher chains measure ~1e-3 relative),
        the engine records ``parity_ok=False`` (counted
        ``serve_parity_unverified``) and RE-ANCHORS parity on the largest
        bucket's own AOT rows: buckets that disagree with *that* are still
        dropped, so every served answer remains deterministic and
        bucket-independent — degraded from "verified eager-equal" to
        "self-consistent", never to "depends which batch you rode in".
        Served but saying so beats refusing service.  Returns total warmup
        seconds."""
        live = self.buckets()
        if not live:
            return 0.0
        probe = self._probe_batch(live[-1])
        oracle = self.offline(probe)
        total = 0.0
        outs: dict[int, np.ndarray] = {}
        for bucket in live:
            t0 = time.perf_counter()
            with trace.span(
                "serve.warmup", cat="serve", bucket=bucket, label=self.label
            ):
                outs[bucket] = np.asarray(
                    self._execute(
                        bucket, self._h2d(probe[:bucket], bucket)
                    )
                )
            dt = time.perf_counter() - t0
            self.warmup_seconds[bucket] = dt
            total += dt
            self.parity[bucket] = bool(
                np.array_equal(
                    outs[bucket][:bucket], np.asarray(oracle)[:bucket]
                )
            )
        passing = [b for b in live if self.parity.get(b)]
        reason = "rows differ from the eager apply"
        if not passing:
            self.parity_ok = False
            counters.record(
                "serve_parity_unverified",
                f"serve:{self.label}: no bucket reproduced the eager "
                "oracle bit-for-bit — re-anchoring on the largest bucket "
                "(served answers stay self-consistent, not eager-verified)",
            )
            # Self-consistency floor: the largest bucket's AOT rows become
            # the anchor; its own parity flag stays False (it is NOT
            # eager-verified) but it always survives the drop pass.
            anchor = outs[live[-1]]
            passing = [
                b
                for b in live
                if np.array_equal(outs[b][:b], anchor[:b])
            ]
            reason = "rows differ from the largest bucket's AOT apply"
        for bucket in live:
            if bucket in passing:
                continue
            with self._lock:
                self._exec.pop(bucket, None)
            counters.record(
                "serve_bucket_parity_dropped",
                f"serve:{self.label}: bucket {bucket} {reason} "
                "(batch-size-dependent XLA rounding) — dropped so every "
                f"served answer stays deterministic; live {passing}",
            )
        return total

    # -- the inference path ---------------------------------------------------

    def buckets(self) -> tuple[int, ...]:
        """Currently-live buckets, ascending (admission-dropped and
        OOM-retired buckets excluded)."""
        with self._lock:
            return tuple(sorted(self._exec))

    def bucket_for(self, n: int) -> int:
        """Smallest live bucket holding ``n`` requests (the largest bucket
        when ``n`` exceeds it — the caller chunks)."""
        live = self.buckets()
        if not live:
            raise ServingUnavailable(
                f"serve:{self.label}: every bucket OOMed away — the "
                "endpoint has no executable left"
            )
        for b in live:
            if n <= b:
                return b
        return live[-1]

    def _execute(self, bucket: int, dev_batch):
        """Run one bucket's AOT executable (the very program the preflight
        planned — ``plan.compiled``).  Separated out so the chaos harness
        can inject RESOURCE_EXHAUSTED here."""
        with self._lock:
            ex = self._exec.get(bucket)
        if ex is None:
            raise ServingUnavailable(
                f"serve:{self.label}: bucket {bucket} was retired"
            )
        return ex(self._pipe, dev_batch)

    def _profile_bucket(self, bucket: int, wall_seconds: float) -> None:
        """Ledger hook (core.profiler): one synced bucket execution's MFU
        attribution, keyed ``serve:<label>:b<bucket>``.  Caller gates on
        ``profiler.enabled()`` — this is never on the off path."""
        plan = self.memory_plans.get(bucket)
        kprof.record_program(
            f"serve:{self.label}:b{bucket}",
            plan.compiled if plan is not None else None,
            wall_seconds,
        )

    def _retire_bucket(self, bucket: int, why: str) -> None:
        with self._lock:
            self._exec.pop(bucket, None)
            remaining = sorted(self._exec)
        # Retirements land in the metrics registry too (ISSUE 11): one
        # snapshot() shows the endpoint's degradation state alongside the
        # fault ledger's serve_burst_oom count.
        trace.metrics.inc("serve_bucket_retired")
        trace.metrics.gauge("serve_live_buckets", len(remaining))
        counters.record(
            "serve_burst_oom",
            f"serve:{self.label}: bucket {bucket} {why} — degraded to "
            f"buckets {remaining}",
        )
        trace.instant(
            "serve_bucket_retired", bucket=bucket, label=self.label,
            remaining=remaining,
        )

    def arm_drift_baseline(self, baseline: dict | None) -> None:
        """Arm output-drift detection against a fit-time reference sketch
        (the ``numerics_baseline`` entry ``core.checkpoint.save_pipeline``
        persists in the manifest).  None is a no-op — an engine without a
        baseline serves exactly as before."""
        if baseline:
            self.drift = knum.DriftMonitor(self.label, baseline)

    def rearm_drift_baseline(self, baseline: dict | None) -> None:
        """Re-arm drift detection on a NEW fit-time baseline (counted
        ``drift_rearmed``) — the lifecycle hot-swap path.  Unlike
        :meth:`arm_drift_baseline` this resets the live window and the
        latch through :meth:`numerics.DriftMonitor.rearm`, so answers the
        candidate produced during validation/warmup never contaminate the
        post-swap judgment.  None is a no-op; an engine with no monitor
        yet arms one."""
        if not baseline:
            return
        if self.drift is None:
            self.arm_drift_baseline(baseline)
        else:
            self.drift.rearm(baseline)

    def observe_output(self, host_rows, request_ids=None, bucket=None) -> None:
        """Numerics observatory hook on one bucket's ANSWERED rows: a
        tensor-stat probe (request ids as the NaN-provenance map) plus the
        output-drift sketch.  Observation only — the rows are already on
        their way to the callers, bit-unchanged.  One flag check when the
        observatory is off."""
        if not knum.active():
            return
        site = f"serve.{self.label}" + (f".b{bucket}" if bucket else "")
        knum.probe(site, host_rows, request_ids=request_ids)
        if self.drift is not None:
            self.drift.observe(host_rows)

    def _pad(self, host: np.ndarray, bucket: int) -> np.ndarray:
        pad = bucket - host.shape[0]
        if pad <= 0:
            return host
        return np.concatenate(
            [host, np.zeros((pad, *host.shape[1:]), host.dtype)], axis=0
        )

    def infer(self, host_batch: np.ndarray) -> np.ndarray:
        """Answer ``[n, *example_shape]`` host rows through the bucketed
        AOT programs: chunked to the largest live bucket, each chunk
        padded to its bucket, transferred, executed, sliced back to the
        true rows.  A runtime RESOURCE_EXHAUSTED retires the failing
        bucket and re-runs the SAME rows through smaller buckets — the
        caller sees correct answers or a typed error, never neither."""
        host_batch = np.asarray(host_batch)
        n = host_batch.shape[0]
        outs = []
        i = 0
        while i < n:
            bucket = self.bucket_for(n - i)
            chunk = host_batch[i : i + min(bucket, n - i)]
            outs.append(self._infer_chunk(chunk, bucket))
            i += chunk.shape[0]
        return outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)

    def _infer_chunk(self, chunk: np.ndarray, bucket: int) -> np.ndarray:
        k = chunk.shape[0]
        padded = self._pad(chunk, bucket)
        with trace.io_span(
            "serve.h2d", padded.nbytes, cat="serve", bucket=bucket
        ):
            dev = self._h2d(padded, bucket)
        try:
            t_exec = time.perf_counter()
            with trace.span(
                "serve.execute", cat="serve", bucket=bucket, rows=k
            ) as sp:
                out = sp.sync(self._execute(bucket, dev))
            if kprof.enabled():
                # Per-bucket MFU ledger entry (ISSUE 14): the synced
                # execute wall against the very executable the preflight
                # planned.  One enabled() check when the profiler is off.
                self._profile_bucket(bucket, time.perf_counter() - t_exec)
        except Exception as e:  # noqa: BLE001 — only OOM degrades
            # A concurrent caller can retire this bucket between
            # bucket_for() and _execute(); rows re-route below exactly
            # like an own-OOM (no live bucket left -> typed raise).
            retired_race = (
                isinstance(e, ServingUnavailable)
                and bucket not in self.buckets()
            )
            if not kmem.is_oom_error(e) and not retired_race:
                raise
            if not retired_race:
                self._retire_bucket(
                    bucket, "hit RESOURCE_EXHAUSTED at runtime"
                )
            kmem.free_buffers(dev)
            if not self.buckets():
                raise ServingUnavailable(
                    f"serve:{self.label}: burst OOM on the last "
                    f"bucket ({bucket}) — nothing to degrade to"
                ) from e
            # Re-run the same rows through the surviving buckets (several
            # micro-batches when the chunk no longer fits one).
            return self.infer(chunk)
        with trace.io_span(
            "serve.d2h",
            int(getattr(out, "nbytes", 0)), cat="serve", bucket=bucket,
        ):
            host = np.asarray(out)
        self.observe_output(host[:k], bucket=bucket)
        return host[:k]

    def offline(self, host_batch: np.ndarray) -> np.ndarray:
        """The offline oracle: the fitted pipeline applied directly (no
        bucketing, no padding, no AOT path) — what served answers are
        asserted bit-equal against."""
        import jax.numpy as jnp

        return np.asarray(self._oracle_pipe(jnp.asarray(host_batch)))

    def record(self) -> dict:
        """JSON-able engine summary for bench records."""
        from ..parallel.mesh import mesh_desc

        return {
            "label": self.label,
            "config": self.config.record(),
            "example_shape": list(self.example_shape),
            "example_dtype": str(self.example_dtype),
            "mesh": mesh_desc(self.mesh) if self.mesh is not None else None,
            "live_buckets": list(self.buckets()),
            "parity_ok": self.parity_ok,
            "parity": {str(k): v for k, v in self.parity.items()},
            "warmup_seconds": {
                str(k): round(v, 4) for k, v in self.warmup_seconds.items()
            },
            "memory_plans": {
                str(k): p.breakdown() for k, p in self.memory_plans.items()
            },
            # Output-drift verdict (ISSUE 15): None when no fit-time
            # baseline was armed.
            "drift": self.drift.record() if self.drift is not None else None,
        }


def load_engine(
    path: str,
    example,
    *,
    config: ServeConfig | None = None,
    label: str = "pipeline",
    wrap: Callable[[Any], Any] | None = None,
    mesh=None,
) -> tuple[ServingEngine, dict]:
    """Warm-load a fitted pipeline from a ``core.checkpoint`` artifact and
    stand up its serving engine, measuring the fresh-process COLD START:
    restore seconds, per-bucket AOT compile (inside engine build), and the
    warmup inference.  ``wrap`` post-processes the loaded object into the
    servable Transformer (e.g. a workload assembling a checkpointed dict
    of fitted nodes into its apply chain).  ``mesh`` makes the whole round
    trip topology-portable: the checkpoint restores THROUGH
    ``load_pipeline(mesh=)`` (resharded onto the target, even when it was
    recorded under a different topology) and the engine AOT-compiles
    mesh-native on it.  Returns ``(engine, cold_start_record)``."""
    from .checkpoint import load_numerics_baseline, load_pipeline

    t0 = time.perf_counter()
    with trace.span("serve.cold_load", cat="serve", path=path):
        pipe = load_pipeline(path, mesh=mesh)
    t_load = time.perf_counter()
    if wrap is not None:
        pipe = wrap(pipe)
    engine = ServingEngine(
        pipe, example, config=config, label=label, warmup=False, mesh=mesh
    )
    # Output-drift detection (ISSUE 15): arm the monitor from the
    # fit-time reference sketch the checkpoint manifest carries (absent
    # on pre-observatory artifacts — the engine just serves unmonitored).
    engine.arm_drift_baseline(load_numerics_baseline(path))
    t_build = time.perf_counter()
    engine.warmup()
    t_warm = time.perf_counter()
    cold = {
        "checkpoint_load_seconds": round(t_load - t0, 4),
        "compile_seconds": round(t_build - t_load, 4),
        "warmup_seconds": round(t_warm - t_build, 4),
        "cold_start_seconds": round(t_warm - t0, 4),
    }
    if mesh is not None:
        from ..parallel.mesh import mesh_desc

        cold["mesh"] = mesh_desc(mesh)
    trace.instant("serve_cold_start", label=label, **cold)
    return engine, cold


# -- the dynamic request batcher ----------------------------------------------


class ServeFuture:
    """Handle for one submitted request.  ``result()`` blocks until the
    batcher answers (the request's own output slice) or fails it typed.

    Lifecycle telemetry (ISSUE 11): ``request_id`` is minted at
    ``Server.submit`` and rides through every span the request touches
    (queue -> batch assembly -> H2D -> execute -> slice -> answer), and
    ``phases`` holds the per-phase latency decomposition — queue-wait,
    H2D, device-wait (time parked in the in-flight handoff), execute,
    D2H, answer, and the estimated pad overhead — filled when the
    request resolves."""

    __slots__ = (
        "_event", "_value", "_error", "t_submit", "t_answer",
        "request_id", "phases",
    )

    def __init__(self, request_id: int = 0):
        self._event = threading.Event()
        self._value = None
        self._error: BaseException | None = None
        self.t_submit = time.perf_counter()
        self.t_answer = 0.0
        self.request_id = request_id
        self.phases: dict | None = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None):
        if not self._event.wait(timeout):
            raise TimeoutError("request not answered within timeout")
        if self._error is not None:
            raise self._error
        return self._value

    def latency_seconds(self) -> float:
        """Submit-to-answer wall time (valid once done)."""
        return self.t_answer - self.t_submit

    def _resolve(self, value=None, error: BaseException | None = None):
        self._value = value
        self._error = error
        self.t_answer = time.perf_counter()
        self._event.set()


@dataclasses.dataclass
class ServerStats:
    """Counters of one server's lifetime (bench/chaos artifact)."""

    requests: int = 0
    answered: int = 0
    failed: int = 0  #: futures resolved with a typed error (close, OOM floor)
    malformed: int = 0
    batches: int = 0
    flush_full: int = 0  #: flushes triggered by a full largest bucket
    flush_deadline: int = 0  #: flushes triggered by max_wait_ms
    flush_idle: int = 0  #: opportunistic flushes (device pipeline idle)
    padded_rows: int = 0  #: zero rows added to reach bucket sizes
    occupancy_sum: float = 0.0  #: Σ rows/bucket per batch (mean = /batches)
    queue_peak: int = 0

    def occupancy(self) -> float:
        return self.occupancy_sum / self.batches if self.batches else 0.0

    def record(self) -> dict:
        out = dataclasses.asdict(self)
        out["mean_occupancy"] = round(self.occupancy(), 4)
        # Flight-recorder postmortems written this process (core.telemetry)
        # — the serving stats record links straight to the evidence files.
        out["postmortems"] = telemetry.postmortem_paths()
        return out


class Server:
    """The warm online endpoint: submit single requests, get futures.

    A background ASSEMBLER thread collects queued requests into
    bucket-sized micro-batches (flush on full-largest-bucket OR
    ``max_wait_ms`` from the oldest request, whichever first), pads the
    remainder to the nearest bucket, and dispatches the H2D transfer; a
    background EXECUTOR thread runs the bucket's AOT program and answers
    each request with its own output slice in arrival order.  The two
    threads keep :data:`INFLIGHT_BATCHES` micro-batches in flight — batch
    *i+1* transfers while batch *i* executes, the ``core.ingest``
    double-buffer idiom on the request path.

    Use as a context manager (or call :meth:`close`); pending requests at
    close answer :class:`ServingUnavailable`, never hang.
    """

    def __init__(self, engine: ServingEngine, config: ServeConfig | None = None):
        self.engine = engine
        self.config = config or engine.config
        self.stats = ServerStats()
        #: live SLO surface for this endpoint (core.telemetry): rolling
        #: p50/p99/QPS and error-budget burn rate against the
        #: KEYSTONE_SERVE_SLO_MS target; registered so metrics.snapshot()
        #: carries it under the "slo" group.
        self.slo = telemetry.register_slo(engine.label)
        self._next_id = 0
        self._queue: list = []  # pending _Request entries, arrival order
        self._cond = threading.Condition()
        self._stopped = False
        # Futures minted but not yet resolved (answered OR failed): the
        # accounting drain()/outstanding() wait on.  Every resolution path
        # decrements exactly once (the success loop in _run_batch, and
        # _fail_futs for every typed-failure path).
        self._outstanding = 0
        # assembler -> executor handoff (bounded: backpressure keeps at
        # most INFLIGHT_BATCHES transfers ahead of the executor).
        self._inflight: list = []
        self._inflight_cond = threading.Condition()
        # True while the executor thread is inside a batch — read (without
        # the lock, deliberately: a stale read only shifts WHICH trigger
        # flushes, never correctness) by the assembler's idle-flush check.
        self._executing = False
        self._assembler = threading.Thread(
            target=self._assemble_loop, name="keystone-serve-assembler",
            daemon=True,
        )
        self._executor = threading.Thread(
            target=self._execute_loop, name="keystone-serve-executor",
            daemon=True,
        )
        self._assembler.start()
        self._executor.start()

    # -- client surface -------------------------------------------------------

    def submit(self, x) -> ServeFuture:
        """Enqueue one request (shape ``example_shape``).  Malformed
        requests — wrong shape, uncastable dtype, non-finite payload —
        raise :class:`MalformedRequest` HERE, counted, without ever
        entering a batch."""
        arr = self._validate(x)
        with self._cond:
            if self._stopped:
                raise ServingUnavailable("server is closed")
            self._next_id += 1
            fut = ServeFuture(request_id=self._next_id)
            self._queue.append((arr, fut))
            self._outstanding += 1
            self.stats.requests += 1
            self.stats.queue_peak = max(self.stats.queue_peak, len(self._queue))
            trace.metrics.gauge("serve_queue_depth", len(self._queue))
            trace.metrics.gauge("serve_queue_peak", self.stats.queue_peak)
            self._cond.notify_all()
        # The request's birth on the timeline (and in the flight ring):
        # the id minted here is the key every later lifecycle span carries.
        trace.instant(
            "serve.submit", request_id=fut.request_id, label=self.engine.label
        )
        return fut

    def predict(self, x, timeout: float | None = 30.0):
        """Blocking convenience: ``submit`` + ``result``."""
        return self.submit(x).result(timeout)

    def _reject(self, detail: str, message: str, cause=None):
        # stats mutations happen under the same condition lock as every
        # other ServerStats field — a bare += from concurrent submitters
        # would drop increments and let stats.malformed silently disagree
        # with the (lock-protected) counters ledger.
        with self._cond:
            self.stats.malformed += 1
        counters.record("serve_malformed_request", detail)
        raise MalformedRequest(message) from cause

    def _validate(self, x) -> np.ndarray:
        eng = self.engine
        try:
            arr = np.asarray(x)
        except Exception as e:  # noqa: BLE001 — unarrayable payload
            self._reject(
                f"unarrayable payload: {e}",
                f"request is not array-like: {e}",
                cause=e,
            )
        if tuple(arr.shape) != eng.example_shape:
            self._reject(
                f"shape {tuple(arr.shape)} != {eng.example_shape}",
                f"request shape {tuple(arr.shape)} does not match the "
                f"endpoint's example shape {eng.example_shape}",
            )
        try:
            arr = arr.astype(eng.example_dtype, casting="same_kind", copy=False)
        except TypeError:
            self._reject(
                f"dtype {arr.dtype} not castable to {eng.example_dtype}",
                f"request dtype {arr.dtype} is not same-kind castable to "
                f"{eng.example_dtype}",
            )
        if arr.dtype.kind in "fc" and not np.isfinite(arr).all():
            self._reject(
                "non-finite payload",
                "request payload contains NaN/Inf — refusing to serve a "
                "prediction from a poisoned input",
            )
        return arr

    # -- assembler thread -----------------------------------------------------

    def _pipeline_idle(self) -> bool:
        """No batch in the H2D handoff and none executing — waiting longer
        buys zero occupancy, so a pending batch should go NOW."""
        return not self._inflight and not self._executing

    def _collect(self) -> list | None:
        """Block until a micro-batch is due: full largest bucket, the
        oldest request aged past ``max_wait_ms``, or (``eager_flush``) the
        device pipeline went idle with requests pending.  None at
        shutdown."""
        max_batch = self.config.max_batch
        max_wait = self.config.max_wait_ms / 1e3
        with self._cond:
            while True:
                if self._queue:
                    oldest = self._queue[0][1].t_submit
                    if len(self._queue) >= max_batch:
                        self.stats.flush_full += 1
                        reason = "full"
                    elif time.perf_counter() - oldest >= max_wait:
                        self.stats.flush_deadline += 1
                        reason = "deadline"
                    elif self.config.eager_flush and self._pipeline_idle():
                        self.stats.flush_idle += 1
                        reason = "idle"
                    else:
                        reason = None
                    if reason is None:
                        remaining = max_wait - (time.perf_counter() - oldest)
                        self._cond.wait(min(remaining, _POLL_SECONDS))
                        continue
                    batch = self._queue[:max_batch]
                    del self._queue[:max_batch]
                    trace.metrics.gauge("serve_queue_depth", len(self._queue))
                    # Flush reasons are registry counters too, so one
                    # snapshot() shows the batcher's trigger mix.
                    trace.metrics.inc(f"serve_flush_{reason}")
                    trace.instant(
                        "serve_flush", reason=reason, rows=len(batch),
                        queued=len(self._queue),
                    )
                    return batch
                if self._stopped:
                    return None
                self._cond.wait(_POLL_SECONDS)

    def _assemble_loop(self) -> None:
        try:
            while True:
                batch = self._collect()
                if batch is None:
                    break
                all_rows = np.stack([arr for arr, _ in batch])
                all_futs = [fut for _, fut in batch]
                # Chunk by the CURRENT largest live bucket: after a burst-OOM
                # retirement the collected batch can exceed every surviving
                # bucket, and an oversized batch must become several
                # micro-batches, never a wrong-shaped AOT argument.
                stop = False
                i = 0
                while i < all_rows.shape[0] and not stop:
                    bucket = self.engine.bucket_for(all_rows.shape[0] - i)
                    take = min(bucket, all_rows.shape[0] - i)
                    rows = all_rows[i : i + take]
                    futs = all_futs[i : i + take]
                    i += take
                    n = rows.shape[0]
                    t_assembled = time.perf_counter()
                    padded = self.engine._pad(rows, bucket)
                    pad = padded.shape[0] - n
                    self.stats.padded_rows += pad
                    if pad:
                        trace.metrics.inc("serve_padded_rows", pad)
                    # Dispatch the H2D NOW (async) — it overlaps the
                    # executor's work on the previous micro-batch.  The
                    # span carries the micro-batch's request-id range so a
                    # postmortem can tie a transfer to its victims.
                    with trace.io_span(
                        "serve.h2d", padded.nbytes, cat="serve", bucket=bucket,
                        req_first=futs[0].request_id,
                        req_last=futs[-1].request_id,
                    ):
                        dev = self.engine._h2d(padded, bucket)
                    t_h2d_done = time.perf_counter()
                    entry = (futs, rows, dev, bucket, t_assembled, t_h2d_done)
                    with self._inflight_cond:
                        while (
                            len(self._inflight) >= INFLIGHT_BATCHES
                            and not self._stopped
                        ):
                            self._inflight_cond.wait(_POLL_SECONDS)
                        if self._stopped:
                            # Close raced a collected batch: fail the chunk
                            # in hand AND every not-yet-chunked future of
                            # this batch — all_futs[i:] would otherwise
                            # never be resolved by anyone (the queue no
                            # longer holds them), leaving their callers
                            # blocked forever.
                            self._fail_futs(
                                futs + all_futs[i:],
                                ServingUnavailable("server closed mid-batch"),
                            )
                            stop = True
                        else:
                            self._inflight.append(entry)
                            self._inflight_cond.notify_all()
                if stop:
                    break
        except BaseException as e:  # noqa: BLE001 — never die silently
            _logger.exception("serve assembler thread failed")
            self._shutdown(error=e)
        finally:
            with self._inflight_cond:
                self._inflight.append(None)  # end-of-stream for the executor
                self._inflight_cond.notify_all()

    # -- executor thread ------------------------------------------------------

    def _execute_loop(self) -> None:
        while True:
            with self._inflight_cond:
                while not self._inflight:
                    self._inflight_cond.wait(_POLL_SECONDS)
                entry = self._inflight.pop(0)
                self._executing = entry is not None
                self._inflight_cond.notify_all()
            if entry is None:
                break
            try:
                self._run_batch(entry)
            finally:
                self._executing = False
                # Wake the assembler promptly: the pipeline just went idle,
                # which is itself a flush trigger under eager_flush.
                with self._cond:
                    self._cond.notify_all()

    def _run_batch(self, entry) -> None:
        futs, rows, dev, bucket, t_assembled, t_h2d_done = entry
        n = len(futs)
        degraded = False
        t_exec_start = time.perf_counter()
        try:
            try:
                with trace.span(
                    "serve.execute", cat="serve", bucket=bucket, rows=n,
                    req_first=futs[0].request_id,
                    req_last=futs[-1].request_id,
                ) as sp:
                    out = sp.sync(self.engine._execute(bucket, dev))
                t_exec = time.perf_counter()
                if kprof.enabled():
                    self.engine._profile_bucket(bucket, t_exec - t_exec_start)
                with trace.io_span(
                    "serve.d2h",
                    int(getattr(out, "nbytes", 0)), cat="serve", bucket=bucket,
                ):
                    host = np.asarray(out)
                t_d2h = time.perf_counter()
            except Exception as e:  # noqa: BLE001 — OOM degrades, in-line
                # Retirement race: the previous batch's OOM retired this
                # bucket while THIS batch was already assembled/in flight
                # (the double buffer keeps INFLIGHT_BATCHES ahead) — its
                # rows re-route like the OOM batch's own, they are not
                # failures.  A ServingUnavailable with live buckets
                # remaining is exactly that race; with none left, infer()
                # below re-raises it and the futures fail typed.
                retired_race = (
                    isinstance(e, ServingUnavailable)
                    and bucket not in self.engine.buckets()
                )
                if not kmem.is_oom_error(e) and not retired_race:
                    raise
                if not retired_race:
                    self.engine._retire_bucket(
                        bucket, "hit RESOURCE_EXHAUSTED under burst traffic"
                    )
                kmem.free_buffers(dev)
                # Same rows, smaller buckets — answers stay correct, the
                # endpoint stays up (the tf-serving degradation ladder).
                host = self.engine.infer(rows)
                t_exec = t_d2h = time.perf_counter()
                degraded = True
        except BaseException as e:  # noqa: BLE001 — typed delivery
            counters.record(
                "serve_batch_failed", f"{type(e).__name__}: {e}"
            )
            self._fail_futs(futs, e)
            return
        if not degraded:
            # Numerics observatory: probe + drift-sketch this bucket's
            # answered rows with their request ids as provenance.  The
            # degraded path already observed through infer()'s own chunks
            # — observing again would double-count the sketch.
            self.engine.observe_output(
                host[:n],
                request_ids=[f.request_id for f in futs],
                bucket=bucket,
            )
        self.stats.batches += 1
        self.stats.answered += n
        self.stats.occupancy_sum += n / bucket
        trace.metrics.inc("serve_batches")
        trace.metrics.observe("serve_batch_occupancy", n / bucket)
        trace.metrics.gauge("serve_mean_occupancy", self.stats.occupancy())
        pad = bucket - n if bucket > n else 0
        execute_ms = (t_exec - t_exec_start) * 1e3
        now = time.perf_counter()
        for i, fut in enumerate(futs):
            # Per-phase latency decomposition (ISSUE 11), recorded on the
            # future itself: where did this request's latency go?
            # queue-wait (submit -> batch assembly), H2D, device-wait
            # (parked in the in-flight handoff behind the previous
            # micro-batch), execute, D2H, answer (slice + resolve), plus
            # the pad overhead estimate (execute time bought for zero
            # rows: execute_ms * pad/bucket).
            queue_ms = (t_assembled - fut.t_submit) * 1e3
            latency_ms = (now - fut.t_submit) * 1e3
            fut.phases = {
                "request_id": fut.request_id,
                "bucket": bucket,
                "rows": n,
                "pad_rows": pad,
                "queue_wait_ms": round(queue_ms, 3),
                "h2d_ms": round((t_h2d_done - t_assembled) * 1e3, 3),
                "device_wait_ms": round(
                    (t_exec_start - t_h2d_done) * 1e3, 3
                ),
                "execute_ms": round(execute_ms, 3),
                "d2h_ms": round((t_d2h - t_exec) * 1e3, 3),
                "answer_ms": round((now - t_d2h) * 1e3, 3),
                "pad_overhead_ms": round(execute_ms * pad / bucket, 3),
                "latency_ms": round(latency_ms, 3),
            }
            if degraded:
                fut.phases["degraded"] = True
            fut._resolve(value=host[i])
            self.slo.observe(latency_ms, ok=True)
            trace.metrics.observe("serve_latency_ms", latency_ms)
            trace.metrics.observe("serve_queue_wait_ms", queue_ms)
            trace.metrics.observe("serve_device_wait_ms",
                                  fut.phases["device_wait_ms"])
            trace.metrics.observe("serve_execute_ms", execute_ms)
            trace.metrics.inc("serve_requests")
            # One span per REQUEST carrying its phase breakdown — the
            # span itself is point-like on the executor lane; the real
            # intervals live on the serve.h2d/execute/d2h spans above.
            with trace.span("serve.request", cat="serve") as sp:
                sp.set(**fut.phases)
        with self._cond:
            self._outstanding -= n
            self._cond.notify_all()

    def _fail_futs(self, futs, error: BaseException) -> None:
        now = time.perf_counter()
        resolved = 0
        for fut in futs:
            if not fut.done():
                fut._resolve(error=error)
                resolved += 1
                # A typed failure burns error budget like an SLO miss.
                self.slo.observe((now - fut.t_submit) * 1e3, ok=False)
        if resolved:
            with self._cond:
                self.stats.failed += resolved
                self._outstanding -= resolved
                self._cond.notify_all()

    # -- lifecycle ------------------------------------------------------------

    def _shutdown(self, error: BaseException | None = None) -> None:
        with self._cond:
            self._stopped = True
            pending = list(self._queue)
            self._queue.clear()
            self._cond.notify_all()
        with self._inflight_cond:
            self._inflight_cond.notify_all()
        err = error or ServingUnavailable(
            "server closed with requests still pending"
        )
        self._fail_futs([fut for _, fut in pending], err)

    def outstanding(self) -> int:
        """Futures minted by :meth:`submit` and not yet resolved (answered
        or typed-failed)."""
        with self._cond:
            return self._outstanding

    def drain(self, timeout: float = 30.0) -> bool:
        """Block until every submitted request has been RESOLVED (answered
        or typed-failed) — the graceful-retire primitive: a router stops
        routing to this server, drains it, then closes it, so an engine
        swap never drops a request.  Returns False on timeout."""
        end = time.monotonic() + timeout
        with self._cond:
            while self._outstanding > 0:
                remaining = end - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, _POLL_SECONDS))
        return True

    def close(self) -> None:
        """Stop accepting requests; pending/in-flight requests answer
        :class:`ServingUnavailable`.  Idempotent."""
        self._shutdown()

    def join(self, timeout: float = 10.0) -> bool:
        """Wait for both serving threads to exit (the no-leak assertion
        the tier-1 suite runs).  Call after :meth:`close`."""
        end = time.monotonic() + timeout
        self._assembler.join(max(0.0, end - time.monotonic()))
        self._executor.join(max(0.0, end - time.monotonic()))
        return not (self._assembler.is_alive() or self._executor.is_alive())

    def __enter__(self) -> "Server":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
        self.join()


# -- the SLO bench ------------------------------------------------------------


def _percentile(sorted_vals: Sequence[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    return float(sorted_vals[min(len(sorted_vals) - 1, int(q * len(sorted_vals)))])


#: The request-lifecycle phases aggregated by :func:`phase_breakdown`.
PHASE_KEYS = (
    "queue_wait_ms", "h2d_ms", "device_wait_ms", "execute_ms", "d2h_ms",
    "answer_ms", "pad_overhead_ms",
)


def phase_breakdown(phases: Sequence[dict]) -> dict:
    """Aggregate per-request phase decompositions (``ServeFuture.phases``)
    into mean/p99 per phase — the tf.data-style bottleneck attribution for
    the request path (which phase to fix to move p99)."""
    out: dict = {"requests": len(phases)}
    for key in PHASE_KEYS:
        vals = sorted(p[key] for p in phases if key in p)
        if not vals:
            continue
        out[key] = {
            "mean": round(sum(vals) / len(vals), 3),
            "p99": round(_percentile(vals, 0.99), 3),
        }
    if phases:
        out["degraded_requests"] = sum(1 for p in phases if p.get("degraded"))
    return out


def serve_bench(
    engine: ServingEngine,
    requests: np.ndarray,
    *,
    clients: int = 4,
    depth: int = 4,
    unbatched_baseline: bool = True,
    timeout: float = 120.0,
) -> dict:
    """Drive ``clients`` concurrent synthetic clients over ``requests``
    (``[N, *example_shape]`` rows, split round-robin; each client keeps
    ``depth`` requests outstanding — the pipelined open-loop pressure a
    real request population puts on an endpoint, and what lets the batcher
    actually fill buckets) and record the online SLOs: p50/p99 latency,
    sustained QPS, batcher occupancy — plus the batched-vs-unbatched QPS
    ratio (the SAME engine behind a flush-per-request server) and
    bit-equality of every served answer against the offline
    ``pipeline(x)`` oracle."""
    requests = np.asarray(requests)
    offline = engine.offline(requests)
    # When the chain failed eager-parity verification (parity_ok=False,
    # counted at warmup) the honest equality bar is the engine's own
    # bucketed AOT apply: answers must be DETERMINISTIC (identical to a
    # fresh offline pass through the same executables), even though the
    # eager oracle rounds differently.
    aot_oracle = None if engine.parity_ok else engine.infer(requests)

    def drive(server: Server) -> tuple[float, list, np.ndarray, list]:
        lat: list = []
        phases: list = []
        answers: list = [None] * requests.shape[0]
        errors: list = []

        def client(cid: int):
            pending: list = []

            def resolve(fut, i):
                answers[i] = fut.result(timeout)
                lat.append(fut.latency_seconds())
                if fut.phases is not None:
                    phases.append(fut.phases)

            try:
                for i in range(cid, requests.shape[0], clients):
                    pending.append((server.submit(requests[i]), i))
                    if len(pending) >= max(1, depth):
                        resolve(*pending.pop(0))
                for fut, i in pending:
                    resolve(fut, i)
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        threads = [
            threading.Thread(target=client, args=(c,), daemon=True)
            for c in range(clients)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout)
        wall = time.perf_counter() - t0
        if errors:
            raise errors[0]
        return wall, lat, np.stack(answers), phases

    with Server(engine) as server:
        wall, lat, answers, phases = drive(server)
        stats = server.stats
        slo = server.slo.summary()
    lat_ms = sorted(v * 1e3 for v in lat)
    record = {
        "engine": engine.record(),
        "clients": clients,
        "requests": int(requests.shape[0]),
        "qps": round(requests.shape[0] / wall, 2),
        "p50_latency_ms": round(_percentile(lat_ms, 0.50), 3),
        "p99_latency_ms": round(_percentile(lat_ms, 0.99), 3),
        "max_latency_ms": round(lat_ms[-1], 3) if lat_ms else 0.0,
        "batcher": stats.record(),
        # Where the latency went (ISSUE 11): mean/p99 of every request's
        # per-phase decomposition — queue-wait vs device-wait vs pad
        # overhead separable at a glance.
        "phase_breakdown": phase_breakdown(phases),
        # The live SLO surface at bench end: rolling p50/p99/QPS and the
        # error-budget burn rate against KEYSTONE_SERVE_SLO_MS.
        "slo": slo,
        "predictions_bit_identical": bool(np.array_equal(answers, offline)),
    }
    if engine.drift is not None:
        # Output-drift verdict over the benched traffic (ISSUE 15) —
        # per-engine divergence vs the fit-time baseline, the row
        # tools/health_view.py renders.
        record["output_drift"] = engine.drift.record()
    if aot_oracle is not None:
        record["parity_unverified"] = True
        record["predictions_deterministic"] = bool(
            np.array_equal(answers, aot_oracle)
        )
    if unbatched_baseline:
        # Batching OFF, everything else identical: the SAME parity-verified
        # engine behind a server whose flush threshold is one request
        # (max_batch=1, zero wait) — each request rides its own padded
        # micro-batch through the same executables, so the QPS ratio
        # isolates batching amortization, not a recompile.
        un_cfg = ServeConfig(
            buckets=(1,),
            max_wait_ms=0.0,
            donate=engine.config.donate,
            eager_flush=engine.config.eager_flush,
        )
        with Server(engine, config=un_cfg) as server:
            u_wall, _u_lat, u_answers, _u_phases = drive(server)
        record["unbatched_qps"] = round(requests.shape[0] / u_wall, 2)
        record["batched_vs_unbatched_qps"] = round(
            record["qps"] / max(record["unbatched_qps"], 1e-9), 2
        )
        record["unbatched_bit_identical"] = bool(
            np.array_equal(u_answers, offline)
        )
        if aot_oracle is not None:
            record["unbatched_deterministic"] = bool(
                np.array_equal(u_answers, aot_oracle)
            )
    return record
