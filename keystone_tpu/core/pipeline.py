"""Pipeline DSL core — the product surface of the framework.

TPU-native re-design of KeystoneML's pipeline algebra (reference:
src/main/scala/pipelines/Transformer.scala:16-82, Estimator.scala:12-33,
LabelEstimator.scala:13-37, FunctionNode.scala:3).

Design stance (differs from the reference deliberately):

* The reference's ``Transformer[A,B]`` carries an item-level ``apply(A): B``
  and a bulk ``apply(RDD[A]): RDD[B]`` whose default is a lazy per-item map
  (Transformer.scala:22).  On TPU the *batch* is the primitive: a node's
  ``__call__`` takes a batch — a ``jax.Array`` with a leading example axis,
  possibly sharded over the mesh's data axis — and returns a batch.  The
  item-level form is derived (``apply_item``), the opposite default of the
  reference, because batched dense compute is what the MXU wants.
* There is no lazy DAG / scheduler: JAX tracing under ``jax.jit`` *is* the
  DAG, and XLA is the scheduler.  ``Pipeline`` composition is therefore plain
  function composition, and a whole pipeline can be jitted as one program.
* Nodes are pytrees (registered via ``register_node``) so fitted state
  (weights, means, …) flows through ``jax.jit`` / ``shard_map`` untouched.

The composition algebra — ``then`` / ``then_estimator`` /
``then_label_estimator`` (reference Transformer.scala:37-67) — is preserved
verbatim, including the closure semantics of ``thenEstimator``: fitting the
chained estimator first pushes the data through the upstream transformer.
"""

from __future__ import annotations

import abc
import contextlib
import dataclasses
import json
import threading
import time
from typing import Any, Callable, Generic, Sequence, TypeVar

import jax

from . import numerics
from . import trace

A = TypeVar("A")
B = TypeVar("B")
C = TypeVar("C")
L = TypeVar("L")

# Class-name -> (cls, data_fields, meta_fields) for every node registered via
# register_node/@node — the schema the checkpoint serializer (core.checkpoint)
# walks to save and rebuild fitted pipelines by name.
NODE_REGISTRY: dict = {}


def register_node(cls, data_fields: Sequence[str] = (), meta_fields: Sequence[str] = ()):
    """Register a node class as a JAX pytree.

    ``data_fields`` are traced leaves (arrays / fitted state); ``meta_fields``
    are static aux data (shapes, flags).  Nodes with no fields are leaves-free
    static pytrees.
    """
    data_fields = tuple(data_fields)
    meta_fields = tuple(meta_fields)
    NODE_REGISTRY[cls.__name__] = (cls, data_fields, meta_fields)

    def flatten(obj):
        return (
            tuple(getattr(obj, f) for f in data_fields),
            tuple(getattr(obj, f) for f in meta_fields),
        )

    def unflatten(meta, data):
        obj = object.__new__(cls)
        for f, v in zip(data_fields, data):
            object.__setattr__(obj, f, v)
        for f, v in zip(meta_fields, meta):
            object.__setattr__(obj, f, v)
        return obj

    jax.tree_util.register_pytree_node(cls, flatten, unflatten)
    return cls


def node(data_fields: Sequence[str] = (), meta_fields: Sequence[str] = ()):
    """Class decorator form of :func:`register_node`."""

    def deco(cls):
        return register_node(cls, data_fields, meta_fields)

    return deco


class Transformer(Generic[A, B], abc.ABC):
    """A deterministic, chainable function node over batches.

    Mirrors reference Transformer.scala:16-82.  Subclasses implement
    ``__call__(batch)``; ``apply_item`` defaults to batch-of-one.
    """

    @abc.abstractmethod
    def __call__(self, batch: A) -> B:  # pragma: no cover - interface
        ...

    # -- item-level view (the reference's primary form, our derived one) ----
    def apply_item(self, item):
        out = self(item[None])
        return out[0]

    # -- composition algebra (reference Transformer.scala:37-67) ------------
    def then(self, nxt: "Transformer[B, C]") -> "Pipeline[A, C]":
        return Pipeline([self, nxt])

    def __rshift__(self, nxt):
        if isinstance(nxt, Transformer):
            return self.then(nxt)
        if isinstance(nxt, Estimator):
            return self.then_estimator(nxt)
        if isinstance(nxt, LabelEstimator):
            return self.then_label_estimator(nxt)
        return NotImplemented

    def then_function(self, fn: Callable[[B], C]) -> "Pipeline[A, C]":
        return self.then(FunctionTransformer(fn))

    def then_estimator(self, est: "Estimator[B, C]") -> "ChainedEstimator[A, B, C]":
        return ChainedEstimator(self, est)

    def then_label_estimator(
        self, est: "LabelEstimator[B, C, L]"
    ) -> "ChainedLabelEstimator[A, B, C, L]":
        return ChainedLabelEstimator(self, est)


@node(data_fields=(), meta_fields=("fn", "name"))
class FunctionTransformer(Transformer):
    """Wrap a plain function as a Transformer (reference Transformer.scala:75-82)."""

    def __init__(self, fn: Callable, name: str | None = None):
        self.fn = fn
        self.name = name or getattr(fn, "__name__", "fn")

    def __call__(self, batch):
        return self.fn(batch)

    def __repr__(self):
        return f"FunctionTransformer({self.name})"


def transformer(fn: Callable) -> FunctionTransformer:
    """Functional constructor, the reference's ``Transformer(f)`` companion."""
    return FunctionTransformer(fn)


# -- per-node reuse tracking (the auto-Cacher's fit-path measurement) ---------

_reuse_tls = threading.local()


@contextlib.contextmanager
def track_reuse():
    """Count node executions by object identity while the block runs.

    Yields a dict mapping ``id(node) -> execution count``, filled in as
    pipelines run.  This is how the cost-based optimizer (core.optimize)
    measures REUSE: run the workload's fit pattern on a sample under the
    tracker — e.g. ``ChainedEstimator.fit`` pushes data through the
    upstream transformer once, and applying the returned fitted pipeline
    pushes it through again — and a node counted twice is an intermediate
    that would be recomputed, i.e. a Cacher candidate (KeystoneML's
    PipelineRuntimeEstimator derived the same counts from DAG lineage).

    Per-thread (trackers on other threads are unaffected); nesting is not
    supported — the inner tracker wins until it exits."""
    counts: dict = {}
    prev = getattr(_reuse_tls, "counts", None)
    _reuse_tls.counts = counts
    try:
        yield counts
    finally:
        _reuse_tls.counts = prev


def _record_exec(node, counts) -> None:
    counts[id(node)] = counts.get(id(node), 0) + 1


def _node_label(n: Transformer) -> str:
    """Stable display name for a pipeline node (FunctionTransformers carry
    their wrapped function's name)."""
    name = getattr(n, "name", None)
    if isinstance(name, str) and name:
        return name
    return type(n).__name__


def _output_stats(out) -> tuple[int, str | None, tuple | None, int]:
    """(total_bytes, dtype, shape, leaves) of a node output — a single
    array reports its own dtype/shape, a pytree sums its array leaves."""
    if hasattr(out, "nbytes") and hasattr(out, "shape"):
        return int(out.nbytes), str(out.dtype), tuple(out.shape), 1
    leaves = [
        leaf
        for leaf in jax.tree_util.tree_leaves(out)
        if hasattr(leaf, "nbytes")
    ]
    return sum(int(leaf.nbytes) for leaf in leaves), None, None, len(leaves)


@dataclasses.dataclass
class NodeProfile:
    """Measured profile of one pipeline node on one batch."""

    index: int
    name: str
    seconds: float  #: wall time incl. device sync (when ``sync=True``)
    output_bytes: int
    dtype: str | None  #: None for multi-leaf (pytree) outputs
    shape: tuple | None
    leaves: int = 1

    def record(self) -> dict:
        out = dataclasses.asdict(self)
        out["shape"] = list(self.shape) if self.shape is not None else None
        return out


@dataclasses.dataclass
class PipelineProfile:
    """Per-node time + output-size profile of one ``Pipeline.profile`` run —
    the KeystoneML sampling-profiler analog (PipelineRuntimeEstimator
    measured exactly these two quantities per node to decide caching).  The
    future cost-based auto-``Cacher`` optimizer consumes this: a node whose
    recompute time is large relative to its output bytes is the one worth
    materializing."""

    nodes: list  #: list[NodeProfile], pipeline order
    total_seconds: float
    input_bytes: int
    #: The final output batch (so profiling doubles as an application).
    output: Any = dataclasses.field(default=None, repr=False, compare=False)

    def record(self) -> dict:
        return {
            "total_seconds": round(self.total_seconds, 6),
            "input_bytes": self.input_bytes,
            "nodes": [n.record() for n in self.nodes],
        }

    def to_json(self) -> str:
        """The profile as one JSON document (record-first artifacts: bench
        rows and chaos records embed profiles instead of repr-only objects
        that die with the process).  Round-trips through
        :meth:`from_json` minus the ``output`` batch."""
        return json.dumps(self.record())

    @classmethod
    def from_json(cls, doc: str) -> "PipelineProfile":
        rec = json.loads(doc)
        return cls(
            nodes=[
                NodeProfile(
                    index=n["index"],
                    name=n["name"],
                    seconds=n["seconds"],
                    output_bytes=n["output_bytes"],
                    dtype=n.get("dtype"),
                    shape=tuple(n["shape"]) if n.get("shape") else None,
                    leaves=n.get("leaves", 1),
                )
                for n in rec["nodes"]
            ],
            total_seconds=rec["total_seconds"],
            input_bytes=rec["input_bytes"],
        )

    def summary(self) -> str:
        parts = [
            f"{n.name}: {n.seconds * 1e3:.2f}ms -> {n.output_bytes}B"
            for n in self.nodes
        ]
        return f"profile({self.total_seconds * 1e3:.2f}ms): " + " | ".join(parts)


class Pipeline(Transformer):
    """Composition of transformers; itself a transformer (and a pytree).

    Flattens nested pipelines so ``(a >> b) >> c`` and ``a >> (b >> c)`` are
    the same object shape.  The whole pipeline is one traced function — wrap
    with ``jax.jit(pipe)`` for a single fused XLA program.
    """

    def __init__(self, nodes: Sequence[Transformer]):
        flat: list[Transformer] = []
        for n in nodes:
            if isinstance(n, Pipeline):
                flat.extend(n.nodes)
            else:
                flat.append(n)
        self.nodes = tuple(flat)
        # Positions of memoizing Cacher nodes (auto-inserted by
        # core.optimize): empty for almost every pipeline, so __call__ pays
        # one truthiness check unless caching is actually in play.
        self._memo_cachers = tuple(
            i
            for i, n in enumerate(self.nodes)
            if isinstance(n, Cacher) and getattr(n, "memoize", False)
        )

    def __call__(self, batch):
        counts = getattr(_reuse_tls, "counts", None)
        # Numerics observatory (KEYSTONE_NUMERICS=1): every node boundary
        # is a tensor-stat probe site.  One flag check when off; under jit
        # tracing the probes are inert (XLA owns the values there) — only
        # the eager apply path is observed, which is also the path every
        # bit-parity oracle runs.
        probing = numerics.active() and not isinstance(batch, jax.core.Tracer)
        cachers = self._memo_cachers
        start = 0
        key = None
        if cachers and not isinstance(batch, jax.core.Tracer):
            # Resume from the LAST memoizing Cacher that has this exact
            # input's intermediate cached: the nodes before it — shared by
            # identity with the pipeline the cache was filled through — are
            # not recomputed.  Under jit tracing the memo path is inert
            # (XLA owns buffers there).
            key = batch
            for pos in reversed(cachers):
                hit, value = self.nodes[pos]._memo_lookup(key)
                if hit:
                    batch = value
                    start = pos + 1
                    break
        for i in range(start, len(self.nodes)):
            n = self.nodes[i]
            if counts is not None:
                _record_exec(n, counts)
            batch = n(batch)
            if probing:
                numerics.probe(f"pipeline.{_node_label(n)}", batch)
            if key is not None and i in cachers:
                n._memo_store(key, batch)
        return batch

    def apply_item(self, item):
        for n in self.nodes:
            item = n.apply_item(item)
        return item

    def profile(self, batch, sync: bool = True) -> PipelineProfile:
        """Run the pipeline node-by-node on ``batch``, measuring each
        node's wall time and output bytes/dtype/shape — the measured
        per-node profile KeystoneML's cost-based optimizer caches/
        materializes from.  ``sync=True`` (default) blocks on each node's
        output so a node's time includes ITS device compute instead of
        leaking into the next node's dispatch (eager per-node execution —
        profile a representative batch, don't wrap this in ``jit``).

        Each node is also a ``node:<name>`` trace span (under a
        ``pipeline.profile`` parent) carrying the same numbers, so a
        profile shows up in the ``KEYSTONE_TRACE`` timeline."""
        profiles = []
        in_bytes, _, _, _ = _output_stats(batch)
        t_start = time.perf_counter()
        with trace.span("pipeline.profile", nodes=len(self.nodes)):
            for i, n in enumerate(self.nodes):
                label = _node_label(n)
                with trace.span(f"node:{label}", index=i) as sp:
                    t0 = time.perf_counter()
                    batch = n(batch)
                    if sync:
                        batch = jax.block_until_ready(batch)
                    dt = time.perf_counter() - t0
                    if numerics.active():
                        # The profile pass doubles as a numerics pass: the
                        # same per-node boundaries, under `profile.` sites
                        # so a profiled batch's stats are separable from
                        # live traffic's.
                        numerics.probe(f"profile.{label}", batch)
                    nbytes, dtype, shape, leaves = _output_stats(batch)
                    sp.set(
                        seconds=round(dt, 6),
                        output_bytes=nbytes,
                        dtype=dtype,
                        shape=list(shape) if shape is not None else None,
                    )
                profiles.append(
                    NodeProfile(
                        index=i,
                        name=label,
                        seconds=dt,
                        output_bytes=nbytes,
                        dtype=dtype,
                        shape=shape,
                        leaves=leaves,
                    )
                )
        return PipelineProfile(
            nodes=profiles,
            total_seconds=time.perf_counter() - t_start,
            input_bytes=in_bytes,
            output=batch,
        )

    def __repr__(self):
        return "Pipeline(" + " >> ".join(repr(n) for n in self.nodes) + ")"


jax.tree_util.register_pytree_node(
    Pipeline,
    lambda p: (p.nodes, None),
    lambda _, nodes: Pipeline(list(nodes)),
)


class Estimator(Generic[A, B], abc.ABC):
    """Unlabeled fit: data -> fitted Transformer (reference Estimator.scala:12-33)."""

    @abc.abstractmethod
    def fit(self, data: A) -> Transformer[A, B]:  # pragma: no cover - interface
        ...


class LabelEstimator(Generic[A, B, L], abc.ABC):
    """Labeled fit: (data, labels) -> Transformer (reference LabelEstimator.scala:13-37)."""

    @abc.abstractmethod
    def fit(self, data: A, labels: L) -> Transformer[A, B]:  # pragma: no cover
        ...


class FunctionEstimator(Estimator):
    """Functional constructor for estimators (reference Estimator.scala:21-33)."""

    def __init__(self, fn: Callable[[Any], Transformer]):
        self.fn = fn

    def fit(self, data):
        return self.fn(data)


def _apply_counted(xform: Transformer, data):
    """Apply ``xform`` with reuse tracking for BARE transformers too — a
    Pipeline counts its own nodes, but a single-node xform applied directly
    would otherwise be invisible to :func:`track_reuse`."""
    counts = getattr(_reuse_tls, "counts", None)
    if counts is not None and not isinstance(xform, Pipeline):
        _record_exec(xform, counts)
    return xform(data)


class ChainedEstimator(Estimator):
    """``xform then_estimator est``: fitting first maps data through ``xform``
    and returns ``xform >> est.fit(xform(data))`` (reference Transformer.scala:37-44)."""

    def __init__(self, xform: Transformer, est: Estimator):
        self.xform = xform
        self.est = est

    def fit(self, data):
        fitted = self.est.fit(_apply_counted(self.xform, data))
        return self.xform.then(fitted)


class ChainedLabelEstimator(LabelEstimator):
    """Labeled analog of :class:`ChainedEstimator` (reference Transformer.scala:55-67)."""

    def __init__(self, xform: Transformer, est: LabelEstimator):
        self.xform = xform
        self.est = est

    def fit(self, data, labels):
        fitted = self.est.fit(_apply_counted(self.xform, data), labels)
        return self.xform.then(fitted)


class FunctionNode(Generic[A, B]):
    """A non-item-wise node (reference FunctionNode.scala:3) — e.g. a splitter
    producing a list of feature blocks.  Just a named callable."""

    def __call__(self, arg: A) -> B:
        raise NotImplementedError


@node(data_fields=(), meta_fields=())
class Identity(Transformer):
    """No-op transformer (reference nodes/util/Identity.scala:12-14)."""

    def __call__(self, batch):
        return batch

    def __repr__(self):
        return "Identity()"


@node(data_fields=(), meta_fields=("name", "sharding", "memoize"))
class Cacher(Transformer):
    """Materialization barrier (reference nodes/util/Cacher.scala:13-23).

    Spark's ``.cache()`` becomes: commit the value to device memory (optionally
    with an explicit sharding) and block until resident.  Inside ``jit`` it is
    the identity — XLA manages materialization there.

    ``memoize=True`` (set by the cost-based optimizer, core.optimize) makes
    the barrier also REMEMBER one materialized value, keyed on the identity
    of the *pipeline input* that produced it: Spark's ``.cache()`` meant the
    second pass over the same RDD read the cached partitions instead of
    recomputing the lineage, and the memo reproduces that on the eager path
    — a :class:`Pipeline` containing this node skips the prefix nodes when
    re-applied to the very same input object.  Single-entry by design (the
    fit path's training batch); a different input computes normally and is
    NOT stored, so applying the fitted pipeline to test data never evicts
    the training cache or pins test intermediates.  The memo is runtime
    state, not pytree data — it never flows through jit or checkpoints.
    """

    def __init__(self, name: str | None = None, sharding=None, memoize: bool = False):
        self.name = name
        self.sharding = sharding
        self.memoize = memoize

    def __call__(self, batch):
        if isinstance(batch, jax.core.Tracer):
            return batch  # no-op under trace; XLA owns buffers
        if self.sharding is not None:
            batch = jax.device_put(batch, self.sharding)
        return jax.block_until_ready(batch)

    # -- memo plumbing (driven by Pipeline.__call__, keyed on ITS input) ------

    def _memo_lookup(self, key) -> tuple[bool, Any]:
        memo = getattr(self, "_memo", None)
        if memo is not None and memo[0] is key:
            return True, memo[1]
        return False, None

    def _memo_store(self, key, value) -> None:
        # First-key-wins: the fit path arms the cache with the training
        # batch; later inputs (test data) pass through unmemoized.  The key
        # object is held strongly so its id() can never be reused while the
        # entry lives.
        if getattr(self, "_memo", None) is None:
            self._memo = (key, value)

    def clear_memo(self) -> None:
        """Release the cached intermediate (and its device memory)."""
        self._memo = None

    def __repr__(self):
        return f"Cacher({self.name or ''}{', memoize' if getattr(self, 'memoize', False) else ''})"
