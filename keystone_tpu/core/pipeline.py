"""Pipeline DSL core — the product surface of the framework.

TPU-native re-design of KeystoneML's pipeline algebra (reference:
src/main/scala/pipelines/Transformer.scala:16-82, Estimator.scala:12-33,
LabelEstimator.scala:13-37, FunctionNode.scala:3).

Design stance (differs from the reference deliberately):

* The reference's ``Transformer[A,B]`` carries an item-level ``apply(A): B``
  and a bulk ``apply(RDD[A]): RDD[B]`` whose default is a lazy per-item map
  (Transformer.scala:22).  On TPU the *batch* is the primitive: a node's
  ``__call__`` takes a batch — a ``jax.Array`` with a leading example axis,
  possibly sharded over the mesh's data axis — and returns a batch.  The
  item-level form is derived (``apply_item``), the opposite default of the
  reference, because batched dense compute is what the MXU wants.
* There is no lazy DAG / scheduler: JAX tracing under ``jax.jit`` *is* the
  DAG, and XLA is the scheduler.  ``Pipeline`` composition is therefore plain
  function composition, and a whole pipeline can be jitted as one program.
* Nodes are pytrees (registered via ``register_node``) so fitted state
  (weights, means, …) flows through ``jax.jit`` / ``shard_map`` untouched.

The composition algebra — ``then`` / ``then_estimator`` /
``then_label_estimator`` (reference Transformer.scala:37-67) — is preserved
verbatim, including the closure semantics of ``thenEstimator``: fitting the
chained estimator first pushes the data through the upstream transformer.
"""

from __future__ import annotations

import abc
import dataclasses
import time
from typing import Any, Callable, Generic, Sequence, TypeVar

import jax

from . import trace

A = TypeVar("A")
B = TypeVar("B")
C = TypeVar("C")
L = TypeVar("L")

# Class-name -> (cls, data_fields, meta_fields) for every node registered via
# register_node/@node — the schema the checkpoint serializer (core.checkpoint)
# walks to save and rebuild fitted pipelines by name.
NODE_REGISTRY: dict = {}


def register_node(cls, data_fields: Sequence[str] = (), meta_fields: Sequence[str] = ()):
    """Register a node class as a JAX pytree.

    ``data_fields`` are traced leaves (arrays / fitted state); ``meta_fields``
    are static aux data (shapes, flags).  Nodes with no fields are leaves-free
    static pytrees.
    """
    data_fields = tuple(data_fields)
    meta_fields = tuple(meta_fields)
    NODE_REGISTRY[cls.__name__] = (cls, data_fields, meta_fields)

    def flatten(obj):
        return (
            tuple(getattr(obj, f) for f in data_fields),
            tuple(getattr(obj, f) for f in meta_fields),
        )

    def unflatten(meta, data):
        obj = object.__new__(cls)
        for f, v in zip(data_fields, data):
            object.__setattr__(obj, f, v)
        for f, v in zip(meta_fields, meta):
            object.__setattr__(obj, f, v)
        return obj

    jax.tree_util.register_pytree_node(cls, flatten, unflatten)
    return cls


def node(data_fields: Sequence[str] = (), meta_fields: Sequence[str] = ()):
    """Class decorator form of :func:`register_node`."""

    def deco(cls):
        return register_node(cls, data_fields, meta_fields)

    return deco


class Transformer(Generic[A, B], abc.ABC):
    """A deterministic, chainable function node over batches.

    Mirrors reference Transformer.scala:16-82.  Subclasses implement
    ``__call__(batch)``; ``apply_item`` defaults to batch-of-one.
    """

    @abc.abstractmethod
    def __call__(self, batch: A) -> B:  # pragma: no cover - interface
        ...

    # -- item-level view (the reference's primary form, our derived one) ----
    def apply_item(self, item):
        out = self(item[None])
        return out[0]

    # -- composition algebra (reference Transformer.scala:37-67) ------------
    def then(self, nxt: "Transformer[B, C]") -> "Pipeline[A, C]":
        return Pipeline([self, nxt])

    def __rshift__(self, nxt):
        if isinstance(nxt, Transformer):
            return self.then(nxt)
        if isinstance(nxt, Estimator):
            return self.then_estimator(nxt)
        if isinstance(nxt, LabelEstimator):
            return self.then_label_estimator(nxt)
        return NotImplemented

    def then_function(self, fn: Callable[[B], C]) -> "Pipeline[A, C]":
        return self.then(FunctionTransformer(fn))

    def then_estimator(self, est: "Estimator[B, C]") -> "ChainedEstimator[A, B, C]":
        return ChainedEstimator(self, est)

    def then_label_estimator(
        self, est: "LabelEstimator[B, C, L]"
    ) -> "ChainedLabelEstimator[A, B, C, L]":
        return ChainedLabelEstimator(self, est)


@node(data_fields=(), meta_fields=("fn", "name"))
class FunctionTransformer(Transformer):
    """Wrap a plain function as a Transformer (reference Transformer.scala:75-82)."""

    def __init__(self, fn: Callable, name: str | None = None):
        self.fn = fn
        self.name = name or getattr(fn, "__name__", "fn")

    def __call__(self, batch):
        return self.fn(batch)

    def __repr__(self):
        return f"FunctionTransformer({self.name})"


def transformer(fn: Callable) -> FunctionTransformer:
    """Functional constructor, the reference's ``Transformer(f)`` companion."""
    return FunctionTransformer(fn)


def _node_label(n: Transformer) -> str:
    """Stable display name for a pipeline node (FunctionTransformers carry
    their wrapped function's name)."""
    name = getattr(n, "name", None)
    if isinstance(name, str) and name:
        return name
    return type(n).__name__


def _output_stats(out) -> tuple[int, str | None, tuple | None, int]:
    """(total_bytes, dtype, shape, leaves) of a node output — a single
    array reports its own dtype/shape, a pytree sums its array leaves."""
    if hasattr(out, "nbytes") and hasattr(out, "shape"):
        return int(out.nbytes), str(out.dtype), tuple(out.shape), 1
    leaves = [
        leaf
        for leaf in jax.tree_util.tree_leaves(out)
        if hasattr(leaf, "nbytes")
    ]
    return sum(int(leaf.nbytes) for leaf in leaves), None, None, len(leaves)


@dataclasses.dataclass
class NodeProfile:
    """Measured profile of one pipeline node on one batch."""

    index: int
    name: str
    seconds: float  #: wall time incl. device sync (when ``sync=True``)
    output_bytes: int
    dtype: str | None  #: None for multi-leaf (pytree) outputs
    shape: tuple | None
    leaves: int = 1

    def record(self) -> dict:
        out = dataclasses.asdict(self)
        out["shape"] = list(self.shape) if self.shape is not None else None
        return out


@dataclasses.dataclass
class PipelineProfile:
    """Per-node time + output-size profile of one ``Pipeline.profile`` run —
    the KeystoneML sampling-profiler analog (PipelineRuntimeEstimator
    measured exactly these two quantities per node to decide caching).  The
    future cost-based auto-``Cacher`` optimizer consumes this: a node whose
    recompute time is large relative to its output bytes is the one worth
    materializing."""

    nodes: list  #: list[NodeProfile], pipeline order
    total_seconds: float
    input_bytes: int
    #: The final output batch (so profiling doubles as an application).
    output: Any = dataclasses.field(default=None, repr=False, compare=False)

    def record(self) -> dict:
        return {
            "total_seconds": round(self.total_seconds, 6),
            "input_bytes": self.input_bytes,
            "nodes": [n.record() for n in self.nodes],
        }

    def summary(self) -> str:
        parts = [
            f"{n.name}: {n.seconds * 1e3:.2f}ms -> {n.output_bytes}B"
            for n in self.nodes
        ]
        return f"profile({self.total_seconds * 1e3:.2f}ms): " + " | ".join(parts)


class Pipeline(Transformer):
    """Composition of transformers; itself a transformer (and a pytree).

    Flattens nested pipelines so ``(a >> b) >> c`` and ``a >> (b >> c)`` are
    the same object shape.  The whole pipeline is one traced function — wrap
    with ``jax.jit(pipe)`` for a single fused XLA program.
    """

    def __init__(self, nodes: Sequence[Transformer]):
        flat: list[Transformer] = []
        for n in nodes:
            if isinstance(n, Pipeline):
                flat.extend(n.nodes)
            else:
                flat.append(n)
        self.nodes = tuple(flat)

    def __call__(self, batch):
        for n in self.nodes:
            batch = n(batch)
        return batch

    def apply_item(self, item):
        for n in self.nodes:
            item = n.apply_item(item)
        return item

    def profile(self, batch, sync: bool = True) -> PipelineProfile:
        """Run the pipeline node-by-node on ``batch``, measuring each
        node's wall time and output bytes/dtype/shape — the measured
        per-node profile KeystoneML's cost-based optimizer caches/
        materializes from.  ``sync=True`` (default) blocks on each node's
        output so a node's time includes ITS device compute instead of
        leaking into the next node's dispatch (eager per-node execution —
        profile a representative batch, don't wrap this in ``jit``).

        Each node is also a ``node:<name>`` trace span (under a
        ``pipeline.profile`` parent) carrying the same numbers, so a
        profile shows up in the ``KEYSTONE_TRACE`` timeline."""
        profiles = []
        in_bytes, _, _, _ = _output_stats(batch)
        t_start = time.perf_counter()
        with trace.span("pipeline.profile", nodes=len(self.nodes)):
            for i, n in enumerate(self.nodes):
                label = _node_label(n)
                with trace.span(f"node:{label}", index=i) as sp:
                    t0 = time.perf_counter()
                    batch = n(batch)
                    if sync:
                        batch = jax.block_until_ready(batch)
                    dt = time.perf_counter() - t0
                    nbytes, dtype, shape, leaves = _output_stats(batch)
                    sp.set(
                        seconds=round(dt, 6),
                        output_bytes=nbytes,
                        dtype=dtype,
                        shape=list(shape) if shape is not None else None,
                    )
                profiles.append(
                    NodeProfile(
                        index=i,
                        name=label,
                        seconds=dt,
                        output_bytes=nbytes,
                        dtype=dtype,
                        shape=shape,
                        leaves=leaves,
                    )
                )
        return PipelineProfile(
            nodes=profiles,
            total_seconds=time.perf_counter() - t_start,
            input_bytes=in_bytes,
            output=batch,
        )

    def __repr__(self):
        return "Pipeline(" + " >> ".join(repr(n) for n in self.nodes) + ")"


jax.tree_util.register_pytree_node(
    Pipeline,
    lambda p: (p.nodes, None),
    lambda _, nodes: Pipeline(list(nodes)),
)


class Estimator(Generic[A, B], abc.ABC):
    """Unlabeled fit: data -> fitted Transformer (reference Estimator.scala:12-33)."""

    @abc.abstractmethod
    def fit(self, data: A) -> Transformer[A, B]:  # pragma: no cover - interface
        ...


class LabelEstimator(Generic[A, B, L], abc.ABC):
    """Labeled fit: (data, labels) -> Transformer (reference LabelEstimator.scala:13-37)."""

    @abc.abstractmethod
    def fit(self, data: A, labels: L) -> Transformer[A, B]:  # pragma: no cover
        ...


class FunctionEstimator(Estimator):
    """Functional constructor for estimators (reference Estimator.scala:21-33)."""

    def __init__(self, fn: Callable[[Any], Transformer]):
        self.fn = fn

    def fit(self, data):
        return self.fn(data)


class ChainedEstimator(Estimator):
    """``xform then_estimator est``: fitting first maps data through ``xform``
    and returns ``xform >> est.fit(xform(data))`` (reference Transformer.scala:37-44)."""

    def __init__(self, xform: Transformer, est: Estimator):
        self.xform = xform
        self.est = est

    def fit(self, data):
        fitted = self.est.fit(self.xform(data))
        return self.xform.then(fitted)


class ChainedLabelEstimator(LabelEstimator):
    """Labeled analog of :class:`ChainedEstimator` (reference Transformer.scala:55-67)."""

    def __init__(self, xform: Transformer, est: LabelEstimator):
        self.xform = xform
        self.est = est

    def fit(self, data, labels):
        fitted = self.est.fit(self.xform(data), labels)
        return self.xform.then(fitted)


class FunctionNode(Generic[A, B]):
    """A non-item-wise node (reference FunctionNode.scala:3) — e.g. a splitter
    producing a list of feature blocks.  Just a named callable."""

    def __call__(self, arg: A) -> B:
        raise NotImplementedError


@node(data_fields=(), meta_fields=())
class Identity(Transformer):
    """No-op transformer (reference nodes/util/Identity.scala:12-14)."""

    def __call__(self, batch):
        return batch

    def __repr__(self):
        return "Identity()"


@node(data_fields=(), meta_fields=("name", "sharding"))
class Cacher(Transformer):
    """Materialization barrier (reference nodes/util/Cacher.scala:13-23).

    Spark's ``.cache()`` becomes: commit the value to device memory (optionally
    with an explicit sharding) and block until resident.  Inside ``jit`` it is
    the identity — XLA manages materialization there.
    """

    def __init__(self, name: str | None = None, sharding=None):
        self.name = name
        self.sharding = sharding

    def __call__(self, batch):
        if isinstance(batch, jax.core.Tracer):
            return batch  # no-op under trace; XLA owns buffers
        if self.sharding is not None:
            batch = jax.device_put(batch, self.sharding)
        return jax.block_until_ready(batch)

    def __repr__(self):
        return f"Cacher({self.name or ''})"
