"""Fleet observability plane (ISSUE 20): cross-host metrics aggregation,
fleet statusz, and one-file clock-aligned incident capture.

Every observability tier so far stops at the process boundary — one
registry, one statusz, one flight recorder per process — while the
system itself became multi-process (``HostFleet`` fronts N host-local
routers).  This module sees the fleet as ONE system, in two halves:

* **Agent** — every :class:`~.wire.WireServer` already answers the
  ``T_OBS_SNAPSHOT`` / ``T_OBS_FLIGHT`` frames by calling
  :func:`agent_payload`, so a serving member's existing port IS its obs
  endpoint.  A process with no serving socket runs an :class:`ObsAgent`
  (a wire server whose only job is the obs frames).  The payload carries
  the registry snapshot, the statusz providers, RAW histogram sample
  windows (:meth:`~.trace.Metrics.hist_windows`), the flight-recorder
  ring, and the member's ``trace.now_us`` clock stamp.

* **Collector** (:class:`FleetCollector`) — scrapes all registered
  members every ``KEYSTONE_OBS_INTERVAL_S`` and merges them into
  fleet-level metrics: counters SUMMED (last-known values retained for
  dead members, carried across re-admitted reformed survivors — the
  fleet view is monotone through a member loss), gauges LABELED per
  host, and latency histograms merged from pooled raw sample windows —
  fleet p50/p99 and error-budget burn are computed from the pooled
  observations, never by averaging per-host percentiles (averaging
  percentiles is statistically meaningless; pooling is exact up to the
  bounded window).  The merged view renders as a fleet Prometheus
  exposition with ``host``/``rank`` labels, a fleet ``/statusz``
  (schema-tagged) and ``/healthz`` (a dead member = DEGRADED, counted
  ``obs_member_lost`` — never a collector crash).

**Incident capture** — when any member reports a postmortem-family
fault (its fault ledger moved on a :data:`~.telemetry.POSTMORTEM_KINDS`
kind), or a member dies mid-scrape, the collector pulls the flight ring
from EVERY reachable member within a bounded window
(``KEYSTONE_OBS_WINDOW_S``) and writes ONE schema-tagged incident
bundle (``keystone.incident/1``) whose events are aligned onto the
COLLECTOR's clock via the per-member T_CLOCK offsets — a single
cross-host timeline for a host-loss, refit, or OOM incident where
before there were N disconnected files.  ``tools/fleet_view.py``
renders both the live fleet table and the bundle timeline.

Clock model: :meth:`~.wire.WireClient.clock_sync` estimates
``offset_us`` = member_clock − (collector_clock + rtt/2); a member
timestamp lands on the collector timeline as ``ts − offset_us``.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time

from . import trace
from .resilience import counters

_logger = logging.getLogger("keystone_tpu.fleetobs")

OBS_INTERVAL_ENV = "KEYSTONE_OBS_INTERVAL_S"
OBS_DIR_ENV = "KEYSTONE_OBS_DIR"
OBS_WINDOW_ENV = "KEYSTONE_OBS_WINDOW_S"

DEFAULT_INTERVAL_S = 1.0
DEFAULT_WINDOW_S = 5.0

#: Per-trigger-kind incident-bundle cap per collector (the telemetry
#: postmortem discipline: the FIRST occurrences carry the information; a
#: fault storm repeating one kind must not fill a disk).
MAX_INCIDENTS_PER_KIND = 3

OBS_SCHEMA = "keystone.obs/1"
FLEET_STATUSZ_SCHEMA = "keystone.fleet_statusz/1"
INCIDENT_SCHEMA = "keystone.incident/1"


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        _logger.error("%s=%r is not a number — using %g", name, raw, default)
        return default


# -- the agent payload (served by every WireServer) ---------------------------


def agent_payload(kind: str = "snapshot") -> dict:
    """The per-process observability surface one ``T_OBS_*`` frame ships:
    ``"snapshot"`` = statusz + registry snapshot + raw histogram sample
    windows; ``"flight"`` = the flight-recorder ring.  Both stamped with
    this process's ``trace.now_us`` (the clock the T_CLOCK handshake
    measured) so the collector can align them."""
    from . import telemetry

    out = {
        "schema": OBS_SCHEMA,
        "kind": kind,
        "pid": os.getpid(),
        "time_unix": time.time(),
        "now_us": trace.now_us(),
        "rank": int(os.environ.get("KEYSTONE_DIST_RANK", "0") or 0),
    }
    if kind == "flight":
        out["flight"] = trace.flight_events()
    else:
        out["statusz"] = telemetry.statusz_snapshot()
        out["hist_windows"] = trace.metrics.hist_windows()
    return out


class _NullTarget:
    """Serving target of an obs-only endpoint: every REQUEST is refused
    typed (the port exists for the T_OBS_*/T_CLOCK frames)."""

    def submit(self, arr):
        from .serve import ServingUnavailable

        raise ServingUnavailable("observability-only endpoint serves no model")


class ObsAgent:
    """A standalone obs endpoint for processes WITHOUT a serving wire
    server (fit workers, the bench controller): a
    :class:`~.wire.WireServer` over a null target — the dispatch path
    already answers T_OBS_SNAPSHOT/T_OBS_FLIGHT/T_CLOCK for every wire
    server, so all this adds is the socket."""

    def __init__(self, port: int = 0, *, label: str = "obs"):
        from . import wire

        self._server = wire.WireServer(
            _NullTarget(), port=port, label=f"obs:{label}"
        )
        self.host = self._server.host
        self.port = self._server.port

    def close(self) -> None:
        self._server.close()

    def __enter__(self) -> "ObsAgent":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# -- pooled-window merge math (pure, tested) ----------------------------------


def merge_windows(windows) -> dict:
    """Merge raw histogram windows (``{"count","total","min","max",
    "samples"}``) into one pooled window.  Associative and — because
    :func:`window_summary` sorts the pool before picking percentiles —
    order-independent in every derived statistic."""
    merged = {
        "count": 0, "total": 0.0,
        "min": float("inf"), "max": float("-inf"), "samples": [],
    }
    for w in windows:
        if not w or not w.get("count"):
            continue
        merged["count"] += int(w["count"])
        merged["total"] += float(w["total"])
        merged["min"] = min(merged["min"], float(w["min"]))
        merged["max"] = max(merged["max"], float(w["max"]))
        merged["samples"].extend(float(s) for s in w.get("samples", ()))
    return merged


def window_summary(window: dict) -> dict:
    """``{count, mean, min, max, p50, p90, p99}`` of a (merged) window —
    percentiles picked from the SORTED pooled samples with the same index
    rule as :class:`~.trace._Hist`, so a fleet of one member summarizes
    exactly like the member itself."""
    count = int(window.get("count", 0))
    if not count:
        return {"count": 0}
    s = sorted(window.get("samples", ()))
    if not s:  # counts without samples (window evicted): totals only
        return {
            "count": count,
            "mean": window["total"] / count,
            "min": window["min"],
            "max": window["max"],
        }
    pick = lambda q: s[min(len(s) - 1, int(q * len(s)))]  # noqa: E731
    return {
        "count": count,
        "mean": window["total"] / count,
        "min": window["min"],
        "max": window["max"],
        "p50": pick(0.50),
        "p90": pick(0.90),
        "p99": pick(0.99),
    }


def merge_slo(summaries) -> dict:
    """Fleet error-budget burn from POOLED windows: violation counts and
    request counts sum across members; burn = pooled violation rate /
    budget.  (Averaging per-member burn rates would weight an idle member
    equal to a loaded one.)"""
    count = violations = t_req = t_viol = 0
    slo_ms = budget = None
    for s in summaries:
        if not isinstance(s, dict):
            continue
        w = s.get("window", {})
        count += int(w.get("count", 0))
        violations += int(w.get("violations", 0))
        t = s.get("total", {})
        t_req += int(t.get("requests", 0))
        t_viol += int(t.get("violations", 0))
        slo_ms = s.get("slo_ms", slo_ms)
        budget = s.get("budget", budget)
    rate = violations / count if count else 0.0
    out = {
        "slo_ms": slo_ms,
        "budget": budget,
        "window": {"count": count, "violations": violations,
                   "violation_rate": round(rate, 6)},
        "total": {"requests": t_req, "violations": t_viol},
    }
    if budget:
        out["window"]["burn_rate"] = round(rate / budget, 4)
    return out


def align_events(events, offset_us: float, member: str) -> list:
    """Member flight events re-stamped onto the collector timeline:
    ``ts`` (and nothing else) shifts by ``-offset_us``; the member's own
    stamp is preserved as ``ts_member`` and every event is tagged with
    the member key.  Metadata events (no ts) pass through tagged."""
    out = []
    for ev in events:
        ev = dict(ev)
        ev["member"] = member
        if isinstance(ev.get("ts"), (int, float)):
            ev["ts_member"] = ev["ts"]
            ev["ts"] = ev["ts"] - offset_us
        out.append(ev)
    return out


def _member_key(endpoint) -> str:
    return f"{endpoint[0]}:{endpoint[1]}"


# -- the collector ------------------------------------------------------------


class FleetCollector:
    """Scrape every registered fleet member's obs agent on an interval
    and merge the results into one fleet view (see module docstring).

    Passive by default — :meth:`scrape_once` is directly callable (tests,
    tools); :meth:`start` runs it on ``interval_s`` in a daemon thread.
    Every scrape failure is absorbed: a dead member degrades the fleet
    (``obs_member_lost``, ``/healthz`` says so), it never crashes the
    collector or the serving path."""

    def __init__(
        self,
        endpoints=None,
        *,
        label: str = "fleet",
        interval_s: float | None = None,
        incident_dir: str | None = None,
        window_s: float | None = None,
        timeout: float = 10.0,
    ):
        self.label = label
        self.interval_s = (
            interval_s
            if interval_s is not None
            else _env_float(OBS_INTERVAL_ENV, DEFAULT_INTERVAL_S)
        )
        self.window_s = (
            window_s
            if window_s is not None
            else _env_float(OBS_WINDOW_ENV, DEFAULT_WINDOW_S)
        )
        self.incident_dir = (
            incident_dir
            if incident_dir is not None
            else (os.environ.get(OBS_DIR_ENV, "").strip() or None)
        )
        self.timeout = float(timeout)
        self._lock = threading.RLock()
        self._members: dict[str, dict] = {}
        self._last: dict | None = None
        self._incident_counts: dict[str, int] = {}
        self.incident_paths: list[str] = []
        self.scrapes = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        for ep in endpoints or ():
            self.register(ep)

    # -- membership -----------------------------------------------------------

    def register(self, endpoint, *, rank: int | None = None) -> None:
        """Admit (or RE-admit) a member.  A known endpoint is revived in
        place; if its process was replaced (new pid on the next scrape),
        the dead incarnation's last counters are folded into a carry so
        the fleet sums stay monotone across the restart."""
        if isinstance(endpoint, str):
            host, _, port = endpoint.rpartition(":")
            endpoint = (host or "127.0.0.1", int(port))
        endpoint = (str(endpoint[0]), int(endpoint[1]))
        key = _member_key(endpoint)
        with self._lock:
            m = self._members.get(key)
            if m is not None:
                if not m["alive"]:
                    m["alive"] = True
                    m["client"] = None
                    trace.instant("obs.member_readmit", member=key)
                if rank is not None:
                    m["rank"] = rank
                return
            self._members[key] = {
                "endpoint": endpoint,
                "rank": rank,
                "client": None,
                "alive": True,
                "pid": None,
                "offset_us": None,
                "rtt_us": None,
                "last": None,       # last scraped payload (retained at death)
                "carry": {},        # counters of dead prior incarnations
                "carry_faults": {},
                "prev_faults": {},  # fault ledger at the previous scrape
                "scrapes": 0,
                "failures": 0,
                "last_scrape_unix": None,
            }
        trace.instant("obs.member_register", member=key, rank=rank)

    def members(self) -> dict:
        with self._lock:
            return {
                k: {
                    "endpoint": list(m["endpoint"]),
                    "rank": m["rank"],
                    "alive": m["alive"],
                    "pid": m["pid"],
                    "offset_us": m["offset_us"],
                    "rtt_us": m["rtt_us"],
                    "scrapes": m["scrapes"],
                    "failures": m["failures"],
                    "last_scrape_unix": m["last_scrape_unix"],
                }
                for k, m in self._members.items()
            }

    def _client(self, m):
        from . import wire

        if m["client"] is None:
            m["client"] = wire.WireClient(
                m["endpoint"][0], m["endpoint"][1], timeout=self.timeout
            )
            sync = m["client"].clock_sync(samples=3)
            if sync is not None:
                m["offset_us"] = sync["offset_us"]
                m["rtt_us"] = sync["rtt_us"]
        return m["client"]

    def _mark_lost(self, m, key: str, why: str) -> None:
        if not m["alive"]:
            return
        m["alive"] = False
        try:
            if m["client"] is not None:
                m["client"].close()
        finally:
            m["client"] = None
        counters.record(
            "obs_member_lost", f"{self.label}: {key}: {why}"
        )

    # -- scraping -------------------------------------------------------------

    def _scrape_member(self, key: str, m: dict):
        """One member's snapshot, or None (dead member, counted).  Never
        raises."""
        from . import wire

        try:
            client = self._client(m)
            payload = client.obs_snapshot()
            if payload is None:  # pre-obs member: degrade, stay alive
                m["failures"] += 1
                return None
            if (
                m["pid"] is not None
                and payload.get("pid") != m["pid"]
                and m["last"] is not None
            ):
                # A reformed survivor took this endpoint over: fold the
                # dead incarnation's counters into the carry so fleet
                # sums never step backwards.
                stz = m["last"].get("statusz", {})
                for name, v in (stz.get("counters") or {}).items():
                    m["carry"][name] = m["carry"].get(name, 0) + v
                for name, v in (stz.get("faults") or {}).items():
                    m["carry_faults"][name] = (
                        m["carry_faults"].get(name, 0) + v
                    )
                m["prev_faults"] = {}
                m["offset_us"] = None
                client.close()
                m["client"] = None
                self._client(m)  # re-sync the new incarnation's clock
            m["pid"] = payload.get("pid")
            m["last"] = payload
            m["alive"] = True
            m["scrapes"] += 1
            m["last_scrape_unix"] = time.time()
            return payload
        except (OSError, TimeoutError, wire.WireError) as e:
            m["failures"] += 1
            self._mark_lost(m, key, f"{type(e).__name__}: {e}")
            return None
        except Exception as e:  # noqa: BLE001 — never a collector crash
            m["failures"] += 1
            _logger.exception("obs scrape of %s failed", key)
            self._mark_lost(m, key, f"{type(e).__name__}: {e}")
            return None

    def scrape_once(self) -> dict:
        """Scrape every member, merge, detect incidents.  Returns (and
        retains) the merged fleet snapshot."""
        triggers: list = []
        with self._lock:
            items = list(self._members.items())
            for key, m in items:
                was_alive = m["alive"]
                payload = self._scrape_member(key, m)
                if payload is None:
                    if was_alive and not m["alive"]:
                        triggers.append(
                            ("obs_member_lost", key, "member unreachable")
                        )
                    continue
                # Postmortem-family fault motion IN the member triggers
                # fleet-wide incident capture.  The first scrape only
                # seeds the baseline — a fault that predates this
                # collector is not this collector's incident.
                faults = (
                    payload.get("statusz", {}).get("faults") or {}
                )
                prev = m["prev_faults"]
                for kind, total in faults.items():
                    if (
                        m["scrapes"] > 1
                        and self._postmortem_kind(kind)
                        and total > prev.get(kind, 0)
                    ):
                        triggers.append(
                            (kind, key, f"{kind} {prev.get(kind, 0)} -> "
                             f"{total}")
                        )
                m["prev_faults"] = dict(faults)
            self.scrapes += 1
            merged = self._merge_locked()
            self._last = merged
        for kind, key, detail in triggers[:1]:  # one bundle per pass
            self.capture_incident(kind, member=key, detail=detail)
        return merged

    @staticmethod
    def _postmortem_kind(kind: str) -> bool:
        from . import telemetry

        return kind in telemetry.POSTMORTEM_KINDS

    def _merge_locked(self) -> dict:
        """The fleet-level merge of every member's last payload (callers
        hold the lock).  Dead members contribute their retained last
        snapshot — the fleet view stays monotone through a loss."""
        counters_sum: dict = {}
        faults_sum: dict = {}
        gauges: dict = {}
        windows: dict = {}
        slo_parts: dict = {}
        member_statusz: dict = {}
        alive = lost = 0
        for key, m in self._members.items():
            alive += 1 if m["alive"] else 0
            lost += 0 if m["alive"] else 1
            for name, v in m["carry"].items():
                counters_sum[name] = counters_sum.get(name, 0) + v
            for name, v in m["carry_faults"].items():
                faults_sum[name] = faults_sum.get(name, 0) + v
            payload = m["last"]
            if payload is None:
                continue
            stz = payload.get("statusz", {})
            member_statusz[key] = stz
            for name, v in (stz.get("counters") or {}).items():
                counters_sum[name] = counters_sum.get(name, 0) + v
            for name, v in (stz.get("faults") or {}).items():
                faults_sum[name] = faults_sum.get(name, 0) + v
            for name, v in (stz.get("gauges") or {}).items():
                gauges.setdefault(name, {})[key] = v
            for name, w in (payload.get("hist_windows") or {}).items():
                windows.setdefault(name, []).append(w)
            for lbl, s in (stz.get("slo") or {}).items():
                slo_parts.setdefault(lbl, []).append(s)
        merged_windows = {
            name: merge_windows(ws) for name, ws in windows.items()
        }
        return {
            "schema": FLEET_STATUSZ_SCHEMA,
            "label": self.label,
            "time_unix": time.time(),
            "collector_pid": os.getpid(),
            "scrapes": self.scrapes,
            "members": self.members_locked(),
            "alive": alive,
            "lost": lost,
            "degraded": lost > 0,
            "counters": counters_sum,
            "faults": faults_sum,
            "gauges": gauges,
            "histograms": {
                name: window_summary(w) for name, w in merged_windows.items()
            },
            "hist_windows": merged_windows,
            "slo": {
                lbl: merge_slo(parts) for lbl, parts in slo_parts.items()
            },
            "member_statusz": member_statusz,
        }

    def members_locked(self) -> dict:
        return {
            k: {
                "endpoint": list(m["endpoint"]),
                "rank": m["rank"],
                "alive": m["alive"],
                "pid": m["pid"],
                "offset_us": m["offset_us"],
                "rtt_us": m["rtt_us"],
                "scrapes": m["scrapes"],
                "failures": m["failures"],
                "last_scrape_unix": m["last_scrape_unix"],
            }
            for k, m in self._members.items()
        }

    # -- the fleet surface ----------------------------------------------------

    def fleet_statusz(self, *, include_members: bool = True) -> dict:
        """The last merged fleet snapshot (scraping once if none exists).
        ``include_members=False`` drops the per-member statusz bodies
        (the summary tables keep only the merged view)."""
        with self._lock:
            snap = self._last
        if snap is None:
            snap = self.scrape_once()
        if not include_members:
            snap = {k: v for k, v in snap.items() if k != "member_statusz"}
        return snap

    def fleet_healthz(self) -> dict:
        """Liveness verdict: ``ok`` while any member answers; a dead
        member degrades the fleet, it does not fail the probe."""
        with self._lock:
            total = len(self._members)
            alive = sum(1 for m in self._members.values() if m["alive"])
        return {
            "ok": alive > 0,
            "degraded": alive < total,
            "alive": alive,
            "members": total,
        }

    def fleet_prometheus(self) -> str:
        """The fleet exposition: per-member counters/gauges as
        ``host=``/``rank=``-labeled series (one ``# TYPE`` line per
        metric, one sample per member), plus fleet-level aggregates
        (``keystone_fleet_*``): summed counters, pooled-window histogram
        summaries, and membership gauges."""
        from . import telemetry

        snap = self.fleet_statusz()
        lines: list[str] = []
        with self._lock:
            members = [
                (k, m["rank"], m["last"]) for k, m in self._members.items()
            ]
        # per-member series, grouped per metric so TYPE renders once
        per_counter: dict = {}
        per_gauge: dict = {}
        for key, rank, payload in members:
            if payload is None:
                continue
            stz = payload.get("statusz", {})
            for name, v in (stz.get("counters") or {}).items():
                per_counter.setdefault(name, []).append((key, rank, v))
            for name, v in (stz.get("gauges") or {}).items():
                per_gauge.setdefault(name, []).append((key, rank, v))
        for name in sorted(per_counter):
            m = telemetry._metric_name(name)
            lines.append(f"# TYPE {m} counter")
            for key, rank, v in per_counter[name]:
                lbl = telemetry.render_labels({"host": key, "rank": rank})
                lines.append(f"{m}{lbl} {telemetry._fmt(v)}")
        for name in sorted(per_gauge):
            m = telemetry._metric_name(name)
            lines.append(f"# TYPE {m} gauge")
            for key, rank, v in per_gauge[name]:
                lbl = telemetry.render_labels({"host": key, "rank": rank})
                lines.append(f"{m}{lbl} {telemetry._fmt(v)}")
        # fleet aggregates
        for name in sorted(snap.get("counters", {})):
            m = telemetry._metric_name("fleet", name)
            lines.append(f"# TYPE {m} counter")
            lines.append(f"{m} {telemetry._fmt(snap['counters'][name])}")
        for name in sorted(snap.get("histograms", {})):
            h = snap["histograms"][name]
            m = telemetry._metric_name("fleet", name)
            lines.append(f"# TYPE {m} summary")
            for q in ("p50", "p90", "p99"):
                if q in h:
                    lines.append(
                        f'{m}{{quantile="0.{q[1:]}"}} '
                        f"{telemetry._fmt(h[q])}"
                    )
            count = h.get("count", 0)
            lines.append(
                f"{m}_sum {telemetry._fmt(h.get('mean', 0.0) * count)}"
            )
            lines.append(f"{m}_count {telemetry._fmt(count)}")
        hz = self.fleet_healthz()
        for gname, gval in (
            ("fleet_members", hz["members"]),
            ("fleet_members_alive", hz["alive"]),
            ("fleet_degraded", 1 if hz["degraded"] else 0),
        ):
            m = telemetry._metric_name(gname)
            lines.append(f"# TYPE {m} gauge")
            lines.append(f"{m} {telemetry._fmt(gval)}")
        return "\n".join(lines) + "\n"

    # -- incident capture -----------------------------------------------------

    def capture_incident(
        self, kind: str, *, member: str | None = None, detail: str = ""
    ) -> str | None:
        """Pull flight rings from every reachable member within the
        bounded window and write ONE clock-aligned incident bundle.
        Returns the written path, or None (no incident dir, cap reached,
        or an unwritable bundle — never raises)."""
        if not self.incident_dir:
            return None
        try:
            with self._lock:
                n = self._incident_counts.get(kind, 0)
                if n >= MAX_INCIDENTS_PER_KIND:
                    return None
                self._incident_counts[kind] = n + 1
                items = list(self._members.items())
            t0 = time.monotonic()
            deadline = t0 + max(0.5, self.window_s)
            events: list = []
            rings: dict = {}
            missing: list = []
            for key, m in items:
                if time.monotonic() >= deadline:
                    missing.append(key)
                    continue
                ring = self._pull_flight(key, m)
                if ring is None:
                    missing.append(key)
                    continue
                offset = m["offset_us"] or 0.0
                aligned = align_events(ring["flight"], offset, key)
                events.extend(aligned)
                rings[key] = {
                    "rank": m["rank"],
                    "pid": ring.get("pid"),
                    "offset_us": m["offset_us"],
                    "rtt_us": m["rtt_us"],
                    "events": len(aligned),
                }
            # The collector's OWN ring rides along (offset 0 by
            # definition — events are already on the collector clock).
            own = align_events(trace.flight_events(), 0.0, "collector")
            events.extend(own)
            rings["collector"] = {
                "rank": None, "pid": os.getpid(),
                "offset_us": 0.0, "rtt_us": 0.0, "events": len(own),
            }
            events.sort(
                key=lambda ev: ev.get("ts", float("-inf"))
                if isinstance(ev.get("ts"), (int, float)) else float("-inf")
            )
            bundle = {
                "schema": INCIDENT_SCHEMA,
                "time_unix": time.time(),
                "collector_pid": os.getpid(),
                "label": self.label,
                "trigger": {
                    "kind": kind, "member": member, "detail": detail[:500],
                },
                "window_s": self.window_s,
                "capture_wall_s": round(time.monotonic() - t0, 4),
                "members": rings,
                "missing": missing,
                "fleet": self.fleet_healthz(),
                "events": events,
            }
            os.makedirs(self.incident_dir, exist_ok=True)
            safe = "".join(
                c if c.isalnum() or c == "_" else "_" for c in kind
            )
            path = os.path.join(
                self.incident_dir, f"incident_{safe}_{os.getpid()}_{n}.json"
            )
            trace.atomic_write(path, lambda f: json.dump(bundle, f))
            with self._lock:
                self.incident_paths.append(path)
            counters.record(
                "obs_incident_captured",
                f"{kind}: {len(rings)} ring(s), {len(events)} event(s) "
                f"-> {path}",
            )
            _logger.warning("incident bundle -> %s (trigger %s)", path, kind)
            return path
        except Exception:  # noqa: BLE001 — never break the fault path
            _logger.exception("incident capture for %r failed", kind)
            return None

    def _pull_flight(self, key: str, m: dict):
        """One member's flight payload, or None.  Never raises; a member
        that cannot answer is simply missing from the bundle."""
        from . import wire

        try:
            client = self._client(m)
            return client.obs_flight()
        except (OSError, TimeoutError, wire.WireError) as e:
            self._mark_lost(m, key, f"flight pull: {type(e).__name__}: {e}")
            return None
        except Exception:  # noqa: BLE001
            _logger.exception("flight pull from %s failed", key)
            return None

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "FleetCollector":
        """Run :meth:`scrape_once` every ``interval_s`` on a daemon
        thread.  Idempotent."""
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._loop, name="keystone-obs-collector", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001 — the collector must not die
                _logger.exception("fleet scrape failed")

    def stop(self) -> None:
        """Stop the scrape loop and WAIT for any in-flight scrape: after
        ``stop`` returns, no collector connection is mid-handshake (the
        drills compare connection counters and need that quiescence)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(max(30.0, self.interval_s + 5.0) + self.timeout)
            self._thread = None

    def close(self) -> None:
        self.stop()
        with self._lock:
            for m in self._members.values():
                if m["client"] is not None:
                    try:
                        m["client"].close()
                    except OSError:  # pragma: no cover
                        pass
                    m["client"] = None

    def __enter__(self) -> "FleetCollector":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def record(self) -> dict:
        with self._lock:
            return {
                "label": self.label,
                "interval_s": self.interval_s,
                "scrapes": self.scrapes,
                "members": self.members_locked(),
                "incidents": list(self.incident_paths),
            }
