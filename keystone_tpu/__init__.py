"""keystone_tpu — a TPU-native (JAX/XLA/Pallas/pjit) large-scale ML pipeline
framework with the capabilities of KeystoneML (reference: /root/reference).

Layer map (SURVEY.md §1 -> here):
  L0  Breeze/netlib BLAS        -> XLA on the MXU (jnp / lax)
  L0' C++ JNI featurizers       -> Pallas/XLA kernels (ops.sift, ops.fisher, solvers.gmm)
  L1  Spark RDD substrate       -> sharded jax.Array over a device Mesh (parallel.mesh)
  L1' ml-matrix solvers         -> solvers.normal_equations / solvers.block
  L2  Pipeline DSL              -> core.pipeline (Transformer/Estimator algebra)
  L3  Operator nodes            -> ops.*
  L4  Loaders                   -> loaders.* (+ native C++ decode)
  L4' Evaluation                -> evaluation.*
  L5  Application pipelines     -> workloads.*
  L6  CLI launchers             -> python -m keystone_tpu.workloads.<name>

Import discipline: ``import keystone_tpu`` must stay CHEAP — in particular
it must not import jax.  Every spawned decode worker
(core.ingest._decode_worker_main) re-imports this package in a fresh
interpreter, and the old eager ``from .core.checkpoint import ...`` chain
pulled jax (multi-second) into processes that only ever touch numpy/PIL.
The public surface below is therefore resolved lazily via module-level
``__getattr__`` (PEP 562): the first *attribute access* imports the
defining submodule; a bare package import touches nothing.  A subprocess
test (tests/test_lazy_import.py) holds the package to this contract.
"""

from __future__ import annotations

__version__ = "0.1.0"

#: public name -> defining submodule, resolved on first attribute access.
_EXPORTS = {
    # core.checkpoint
    "CheckpointError": "core.checkpoint",
    "checkpoint_exists": "core.checkpoint",
    "load_or_fit": "core.checkpoint",
    "load_pipeline": "core.checkpoint",
    "save_pipeline": "core.checkpoint",
    # core.pipeline
    "Cacher": "core.pipeline",
    "ChainedEstimator": "core.pipeline",
    "ChainedLabelEstimator": "core.pipeline",
    "Estimator": "core.pipeline",
    "FunctionNode": "core.pipeline",
    "FunctionTransformer": "core.pipeline",
    "Identity": "core.pipeline",
    "LabelEstimator": "core.pipeline",
    "Pipeline": "core.pipeline",
    "Transformer": "core.pipeline",
    "transformer": "core.pipeline",
    # core.resilience
    "assert_all_finite": "core.resilience",
    "retry": "core.resilience",
    # parallel.mesh
    "make_mesh": "parallel.mesh",
    "use_mesh": "parallel.mesh",
}

__all__ = sorted((*_EXPORTS, "__version__"))


def __getattr__(name: str):
    mod = _EXPORTS.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    value = getattr(importlib.import_module(f".{mod}", __name__), name)
    # Cache on the package so the lookup (and the import) happens once.
    globals()[name] = value
    return value


def __dir__():
    return __all__
