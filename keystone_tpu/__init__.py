"""keystone_tpu — a TPU-native (JAX/XLA/Pallas/pjit) large-scale ML pipeline
framework with the capabilities of KeystoneML (reference: /root/reference).

Layer map (SURVEY.md §1 -> here):
  L0  Breeze/netlib BLAS        -> XLA on the MXU (jnp / lax)
  L0' C++ JNI featurizers       -> Pallas/XLA kernels (ops.sift, ops.fisher, solvers.gmm)
  L1  Spark RDD substrate       -> sharded jax.Array over a device Mesh (parallel.mesh)
  L1' ml-matrix solvers         -> solvers.normal_equations / solvers.block
  L2  Pipeline DSL              -> core.pipeline (Transformer/Estimator algebra)
  L3  Operator nodes            -> ops.*
  L4  Loaders                   -> loaders.* (+ native C++ decode)
  L4' Evaluation                -> evaluation.*
  L5  Application pipelines     -> workloads.*
  L6  CLI launchers             -> python -m keystone_tpu.workloads.<name>
"""

from .core.checkpoint import (
    CheckpointError,
    checkpoint_exists,
    load_or_fit,
    load_pipeline,
    save_pipeline,
)
from .core.pipeline import (
    Cacher,
    ChainedEstimator,
    ChainedLabelEstimator,
    Estimator,
    FunctionNode,
    FunctionTransformer,
    Identity,
    LabelEstimator,
    Pipeline,
    Transformer,
    transformer,
)
from .core.resilience import assert_all_finite, retry
from .parallel.mesh import make_mesh, use_mesh

__version__ = "0.1.0"

__all__ = [
    "Cacher",
    "ChainedEstimator",
    "ChainedLabelEstimator",
    "CheckpointError",
    "Estimator",
    "FunctionNode",
    "FunctionTransformer",
    "Identity",
    "LabelEstimator",
    "Pipeline",
    "Transformer",
    "assert_all_finite",
    "checkpoint_exists",
    "load_or_fit",
    "load_pipeline",
    "make_mesh",
    "retry",
    "save_pipeline",
    "transformer",
    "use_mesh",
]
