// Native entropy-decode hot loop: a line-for-line port of the Python
// 16-bit-peek LUT scan decoder in ops/jpeg_device._decode_scan.
//
// The device-resident decode path (ops/jpeg_device.py) split baseline JPEG
// at the entropy boundary, but its host half — the Huffman scan decode —
// stayed pure Python and became the live path's CPU bottleneck
// (~30 img/s at 96 px, bench jpeg_decode.by_path).  This translation unit
// is the KeystoneML L0' move (native C++ under the hot host kernel,
// PAPER.md §1) applied to that loop, under an exacting contract:
//
//   * BIT-IDENTICAL coefficient planes: everything that shapes the output
//     — header parsing, Huffman LUT compilation, restart-segment
//     splitting/unstuffing, plane allocation — stays in the SAME Python
//     code (ops/jpeg_device.entropy_decode); only the O(compressed-bytes)
//     symbol loop runs here, writing into the caller's int16 planes with
//     the same zigzag scatter and the same DC prediction.
//   * IDENTICAL typed-error classification: every corrupt-stream check
//     the Python loop performs exists here at the same point in the same
//     order, returned as a KST_E* code that ops/native_entropy.py maps
//     back onto the exact JpegEntropyCorrupt message the Python pass
//     raises.  A stream that fails at MCU k in Python fails at MCU k
//     here with the same classification — the decoders are
//     indistinguishable from the stream contract's point of view.
//
// The function is reentrant and touches no globals, so the ingest thread
// pool drives one call per image across cores; ctypes releases the GIL
// for the duration of each call (the whole point — the Python loop held
// it for the entire scan).
//
// Build: g++ -O2 -shared -fPIC entropy.cpp -o libkstentropy.so
// (see ops/native_entropy.py, which builds lazily and caches the .so;
// deliberately NO libjpeg dependency — the portable-fallback story only
// needs a C++ compiler).

extern "C" {

// Error codes — each maps 1:1 onto a JpegEntropyCorrupt message in
// ops/native_entropy.py (keep the two tables in sync).
enum {
  KST_EOK = 0,
  KST_EINVALID_CODE = 1,   // invalid Huffman code or truncated scan
  KST_EZRL_OVERFLOW = 2,   // ZRL overflows the block
  KST_EAC_OVERFLOW = 3,    // AC run overflows the block
  KST_EDC_CATEGORY = 4,    // DC category out of range
  KST_ETRUNC_COEFF = 5,    // truncated scan mid-coefficient
  KST_EDC_RANGE = 6,       // DC predictor out of int16 range
  KST_ETRUNCATED = 7,      // decoded fewer MCUs than the geometry needs
};

// Decode every MCU of an (already unstuffed, restart-split) scan into the
// caller's per-component coefficient planes.
//
//   segs / seg_lens / nseg   restart segments (stuffing already removed)
//   planes                   per-component int16 plane base pointers,
//                            laid out [block_row][row_width][64]
//   row_width                per-component blocks per plane row
//   mcu_blocks               n_mcu_blocks rows of 7 ints:
//                            (comp, v, h, block_y, block_x, dc_lut, ac_lut)
//   lut_len / lut_sym        per-LUT 65536-entry 16-bit-peek tables
//                            (code length / symbol), indexed by the
//                            mcu_blocks LUT columns
//   zigzag                   64-entry zigzag->natural position table
//   err_info                 out[2]: failing MCU index / DC category
//
// Returns KST_EOK or the KST_E* classification of the damage.
int kst_entropy_decode(
    const unsigned char* const* segs, const long long* seg_lens, int nseg,
    short* const* planes, const int* row_width,
    const int* mcu_blocks, int n_mcu_blocks,
    const unsigned char* const* lut_len,
    const unsigned char* const* lut_sym,
    const unsigned char* zigzag,
    int ncomp, long long mcus_x, long long total_mcus, long long interval,
    long long* err_info) {
  long long preds[4];  // baseline frames carry at most 3 components
  long long mcu = 0;
  for (int s = 0; s < nseg; ++s) {
    const unsigned char* seg = segs[s];
    const long long nbytes = seg_lens[s];
    // Bit reader as plain locals, exactly the Python loop's acc/accbits/
    // pos.  Worst-case accumulator occupancy is 15 held bits + a 6-byte
    // refill = 63 bits, so uint64 never overflows.
    unsigned long long acc = 0;
    int accbits = 0;
    long long pos = 0;
    for (int c = 0; c < ncomp; ++c) preds[c] = 0;
    long long seg_end = mcu + interval;
    if (seg_end > total_mcus) seg_end = total_mcus;
    while (mcu < seg_end) {
      const long long my = mcu / mcus_x;
      const long long mx = mcu % mcus_x;
      for (int b = 0; b < n_mcu_blocks; ++b) {
        const int* mb = mcu_blocks + 7 * b;
        const int ci = mb[0];
        short* row = planes[ci] +
            ((my * mb[1] + mb[3]) * (long long)row_width[ci] +
             mx * mb[2] + mb[4]) * 64;
        long long pred = preds[ci];
        const unsigned char* lenb = lut_len[mb[5]];
        const unsigned char* symb = lut_sym[mb[5]];
        int ac = 0;
        int k = 0;
        for (;;) {
          // -- decode one Huffman symbol --------------------------------
          if (accbits < 16 && pos < nbytes) {
            const long long rem = nbytes - pos;
            const int take = rem < 6 ? (int)rem : 6;
            for (int t = 0; t < take; ++t) acc = (acc << 8) | seg[pos + t];
            accbits += 8 * take;
            pos += take;
          }
          const unsigned peek = (unsigned)(
              (accbits < 16 ? (acc << (16 - accbits))
                            : (acc >> (accbits - 16))) & 0xFFFFu);
          const int nb = lenb[peek];
          if (nb == 0 || nb > accbits) {
            err_info[0] = mcu;
            return KST_EINVALID_CODE;
          }
          accbits -= nb;
          acc &= (1ULL << accbits) - 1;
          const int sym = symb[peek];
          // -- interpret it ---------------------------------------------
          int size;
          if (ac) {
            const int run = sym >> 4;
            size = sym & 0xF;
            if (size == 0) {
              if (run == 15) {
                k += 16;
                if (k > 63) return KST_EZRL_OVERFLOW;
                continue;
              }
              break;  // EOB
            }
            k += run + 1;
            if (k > 63) return KST_EAC_OVERFLOW;
          } else {
            size = sym;
            if (size > 15) {
              err_info[1] = size;
              return KST_EDC_CATEGORY;
            }
          }
          // -- receive the value bits -----------------------------------
          long long val = 0;
          if (size) {
            if (accbits < size) {
              const long long rem = nbytes - pos;
              const int take = rem < 6 ? (rem > 0 ? (int)rem : 0) : 6;
              for (int t = 0; t < take; ++t) acc = (acc << 8) | seg[pos + t];
              accbits += 8 * take;
              pos += take;
              if (accbits < size) return KST_ETRUNC_COEFF;
            }
            accbits -= size;
            val = (long long)((acc >> accbits) & ((1ULL << size) - 1));
            acc &= (1ULL << accbits) - 1;
            if (val < (1LL << (size - 1))) val = val - (1LL << size) + 1;
          }
          if (ac) {
            row[zigzag[k]] = (short)val;
            if (k == 63) break;
          } else {
            pred += val;
            if (pred < -32768 || pred > 32767) return KST_EDC_RANGE;
            row[0] = (short)pred;
            ac = 1;
            lenb = lut_len[mb[6]];
            symb = lut_sym[mb[6]];
          }
        }
        preds[ci] = pred;
      }
      mcu += 1;
    }
  }
  if (mcu != total_mcus) {
    err_info[0] = mcu;
    return KST_ETRUNCATED;
  }
  return KST_EOK;
}

}  // extern "C"
