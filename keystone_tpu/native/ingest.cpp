// Native host-ingest kernel: JPEG -> BGR float32 decode via libjpeg.
//
// The reference's ingest path decodes JPEGs per executor inside the JVM
// (javax ImageIO, reference loaders/ImageLoaderUtils.scala:60-100, with a
// global lock at utils/images/ImageUtils.scala:17); its other native code
// (VLFeat.cxx / EncEval.cxx) lives on the featurization path, which this
// framework re-owns on the TPU.  What genuinely belongs on the host here is
// ingest, so this is the C++ component: a lock-free reentrant decoder with
// a plain C ABI, driven from Python through ctypes.  ctypes releases the
// GIL for the duration of each call, so the existing thread-pool loader
// (loaders/image_loaders.py) gets true multi-core decode with no Python
// image library on the hot path.
//
// Semantics mirror loaders/image_loaders.decode_image exactly: output is
// H x W x 3 float32 BGR in [0, 255] (the reference's ByteArrayVectorizedImage
// is BGR); grayscale is triplicated (ImageConversions.scala:26-37); images
// smaller than 36 px on a side are rejected (ImageUtils.scala:23-27).
//
// Build: g++ -O2 -shared -fPIC ingest.cpp -o libkstingest.so -ljpeg
// (see loaders/native_decode.py, which builds lazily and caches the .so).

#include <csetjmp>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include <jpeglib.h>

namespace {

constexpr int kMinDim = 36;  // reference ImageUtils.loadImage floor

struct ErrorTrap {
  jpeg_error_mgr mgr;
  jmp_buf jump;
};

void error_exit_trap(j_common_ptr cinfo) {
  ErrorTrap* trap = reinterpret_cast<ErrorTrap*>(cinfo->err);
  longjmp(trap->jump, 1);
}

void silence_output(j_common_ptr) {}

}  // namespace

extern "C" {

// Decode a JPEG byte buffer.  On success returns 0 and sets *out (malloc'd
// H*W*3 float32 BGR buffer — free with kst_free), *h, *w.  Returns:
//   1  decode error (corrupt/unsupported stream)
//   2  image rejected (either dimension < 36 px)
//   3  unsupported channel count (not grayscale or 3-channel)
int kst_decode_jpeg(const unsigned char* data, long len, float** out,
                    int* h, int* w) {
  *out = nullptr;
  jpeg_decompress_struct cinfo;
  ErrorTrap trap;
  cinfo.err = jpeg_std_error(&trap.mgr);
  trap.mgr.error_exit = error_exit_trap;
  trap.mgr.output_message = silence_output;

  // volatile: modified between setjmp and longjmp — without it their
  // post-longjmp values are indeterminate (C++ [support.runtime]), so the
  // corrupt-stream error path could leak or free garbage (libjpeg
  // example.c uses the same pattern).
  float* volatile pixels = nullptr;
  unsigned char* volatile row = nullptr;
  if (setjmp(trap.jump)) {
    jpeg_destroy_decompress(&cinfo);
    std::free(pixels);
    std::free(row);
    return 1;
  }

  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(data),
               static_cast<unsigned long>(len));
  jpeg_read_header(&cinfo, TRUE);
  jpeg_start_decompress(&cinfo);

  const int height = static_cast<int>(cinfo.output_height);
  const int width = static_cast<int>(cinfo.output_width);
  const int nc = cinfo.output_components;
  if (height < kMinDim || width < kMinDim) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return 2;
  }
  if (nc != 1 && nc != 3) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return 3;
  }

  pixels = static_cast<float*>(
      std::malloc(sizeof(float) * static_cast<size_t>(height) * width * 3));
  row = static_cast<unsigned char*>(
      std::malloc(static_cast<size_t>(width) * nc));
  if (pixels == nullptr || row == nullptr) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    std::free(pixels);
    std::free(row);
    return 1;
  }

  while (cinfo.output_scanline < cinfo.output_height) {
    const int y = static_cast<int>(cinfo.output_scanline);
    JSAMPROW rows[1] = {row};
    jpeg_read_scanlines(&cinfo, rows, 1);
    float* dst = pixels + static_cast<size_t>(y) * width * 3;
    if (nc == 3) {
      // libjpeg emits RGB; the framework's image layout is BGR
      for (int x = 0; x < width; ++x) {
        dst[x * 3 + 0] = static_cast<float>(row[x * 3 + 2]);
        dst[x * 3 + 1] = static_cast<float>(row[x * 3 + 1]);
        dst[x * 3 + 2] = static_cast<float>(row[x * 3 + 0]);
      }
    } else {
      for (int x = 0; x < width; ++x) {
        const float v = static_cast<float>(row[x]);
        dst[x * 3 + 0] = v;
        dst[x * 3 + 1] = v;
        dst[x * 3 + 2] = v;
      }
    }
  }

  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  std::free(row);
  *out = pixels;
  *h = height;
  *w = width;
  return 0;
}

void kst_free(float* p) { std::free(p); }

}  // extern "C"
